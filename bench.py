"""Headline benchmark: ResNet50 inference on TPU — throughput, latency, MFU.

Mirrors the reference's measurement protocol — timed-window throughput of
batch-1 streaming inference (reference test/test.py:25-37) against a
single-device predict loop (reference test/local_infer.py:16-23) — and adds
what the reference never measured: a batch sweep, amortized-dispatch
numbers, and model FLOPs utilisation (graph FLOPs / step time / chip peak).

Measurement design (r4).  This chip sits behind a tunnel whose per-sync
round trip is ~76 ms (PROFILE_r04.md), so per-step dispatch+sync — the r3
protocol — measures the tunnel, not the chip.  Each side is therefore
reported two ways:

  * single-chip ``stepwise``: dispatch + block per step (reference
    local_infer protocol, kept for parity/continuity), and
    ``scan``: K forwards fused in one on-device ``lax.scan`` dispatch —
    the chip's true best single-program throughput.  The HONEST baseline
    (``vs_baseline`` denominator) is the best scan number across batch
    sizes, NOT the weak batch-1 stepwise number r3 divided by.
  * pipeline: swept over (chunk, microbatch) with >=2 chunks in flight
    (no per-chunk sync) and whole-chunk result slabs drained to host
    (``SpmdPipeline.push(raw=True)``).

Both sides keep their input device-resident, mirroring the reference
harness re-feeding one image (test/test.py:20-23).

Device handling: this environment reaches its single TPU chip through a
tunnel that admits one client and can wedge indefinitely if a previous
client died holding the grant.  The TPU is therefore probed in a THROWAWAY
SUBPROCESS under a HARD-CAPPED total budget (default 2 probes x 150 s +
15 s backoff, ~5.5 min worst case — env DEFER_BENCH_TPU_TIMEOUT_S /
_ATTEMPTS / _BACKOFF_S).  If no TPU materialises in budget, the bench
prints a parseable ``{"value": null, "tpu_unavailable": true, "last_good":
...}`` line and exits 0 — it must NEVER outlive the driver's capture
window (BENCH_r02/r04 were rc=124/no-output under the old unbounded
retry policy).  Set ``DEFER_BENCH_REQUIRE_TPU=1`` to exit(3) instead;
set ``DEFER_BENCH_CPU=1`` to run the CPU smoke path (tiny model)
explicitly.

Prints exactly one JSON line on stdout:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ..., extras}
"""

import argparse
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def chip_peak_flops(device) -> tuple[str, float]:
    """(generation, bf16 peak FLOP/s) for ``device``; (unknown, 0) if the
    chip can't be identified — MFU is only reported against a real peak."""
    from defer_tpu.utils.hw import identify_chip, peak_flops
    gen = identify_chip(device)
    return gen, peak_flops(gen)


def probe_tpu_subprocess(timeout_s: float) -> tuple[str | None, str]:
    """Try backend init in a throwaway subprocess; (platform_info, diag).

    The subprocess either prints "platform|device_kind|count" and exits 0,
    or is killed at the timeout — leaving THIS process clean either way
    (an in-process hung init can never be unwound).
    """
    code = os.environ.get("DEFER_BENCH_PROBE_CODE") or (
        "import jax; ds = jax.devices(); "
        "print(ds[0].platform, '|', getattr(ds[0], 'device_kind', ''), "
        "'|', len(ds))"
    )
    t0 = time.perf_counter()
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True,
                           timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return None, f"probe timed out after {timeout_s:.0f}s (tunnel wedged?)"
    dt = time.perf_counter() - t0
    if r.returncode != 0:
        tail = (r.stderr or "").strip().splitlines()[-3:]
        return None, f"probe exited rc={r.returncode} in {dt:.0f}s: {tail}"
    out = (r.stdout or "").strip().splitlines()
    return (out[-1] if out else None), f"probe ok in {dt:.0f}s"


def init_devices():
    """``jax.devices()`` behind a subprocess probe with retries/backoff."""
    if os.environ.get("DEFER_BENCH_CPU") == "1":
        # explicit CPU smoke run: 8 virtual devices, tiny model
        if "--xla_force_host_platform_device_count" not in \
                os.environ.get("XLA_FLAGS", ""):
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + " --xla_force_host_platform_device_count=8")
        os.environ["PALLAS_AXON_POOL_IPS"] = ""  # skip TPU plugin entirely
        import jax
        jax.config.update("jax_platforms", "cpu")
        return jax.devices()

    # NOTE on the probe-kill tradeoff: killing a probe at its timeout risks
    # leaving a dead client on the single-client tunnel if the probe had
    # already acquired the device grant (it normally hangs *waiting* for
    # it).  There is no graceful way to unwind a C++-level hang, and not
    # probing at all means no TPU number ever.  The TOTAL probe budget is
    # hard-capped (default 2 x 150 s + 15 s backoff ~ 5.5 min) so a wedged
    # tunnel yields a fast, parseable "tpu unavailable" JSON line instead
    # of outliving the driver's capture window (BENCH_r02/r04 were rc=124
    # for exactly that reason).
    attempts = int(os.environ.get("DEFER_BENCH_TPU_ATTEMPTS", "2"))
    timeout_s = float(os.environ.get("DEFER_BENCH_TPU_TIMEOUT_S", "150"))
    backoff_s = float(os.environ.get("DEFER_BENCH_TPU_BACKOFF_S", "15"))
    require = os.environ.get("DEFER_BENCH_REQUIRE_TPU") == "1"
    deadline = time.monotonic() + attempts * timeout_s + (attempts - 1) * \
        backoff_s + 30.0  # absolute ceiling, belt over the per-probe caps

    ok = False
    diag = "no probe attempted"
    for i in range(attempts):
        budget = min(timeout_s, deadline - time.monotonic())
        if budget <= 0:
            diag = "total probe budget exhausted"
            break
        info, diag = probe_tpu_subprocess(budget)
        log(f"bench: tpu probe {i + 1}/{attempts}: {diag}"
            + (f" -> {info}" if info else ""))
        if info is not None and not info.startswith("cpu"):
            ok = True
            break
        if info is not None:  # probe came back, but only a CPU backend
            diag = f"probe found no TPU (backend: {info})"
            break
        if i + 1 < attempts and time.monotonic() + backoff_s < deadline:
            log(f"bench: backing off {backoff_s:.0f}s before retry")
            time.sleep(backoff_s)

    if ok:
        # the probe released the grant cleanly; init here should be fast —
        # but guard with the same timeout in case the tunnel re-wedged
        box = {}

        def _init():
            try:
                import jax
                box["devices"] = jax.devices()
            except Exception as e:  # noqa: BLE001 — report and fall back
                box["error"] = e

        th = threading.Thread(target=_init, daemon=True)
        th.start()
        th.join(max(5.0, min(timeout_s, deadline - time.monotonic())))
        if "devices" in box:
            return box["devices"]
        diag = (f"in-process init failed after successful probe "
                f"({box.get('error', 'timed out')})")
        log(f"bench: {diag}")

    if require:
        log("bench: DEFER_BENCH_REQUIRE_TPU=1 and no TPU; exiting 3")
        sys.exit(3)
    emit_unavailable_and_exit(diag)


def emit_unavailable_and_exit(diag: str):
    """No TPU within budget: print ONE parseable JSON line and exit 0.

    The driver's scoreboard parses stdout for a single JSON object; a
    wedged tunnel must degrade to this line (with the last known-good TPU
    number attached for context), never to rc=124 with no output.
    """
    last_good = None
    here = os.path.dirname(os.path.abspath(__file__))
    for name in ("BENCH_r05_best.json", "BENCH_r05_builder.json",
                 "BENCH_r04_builder.json", "BENCH_r03.json"):
        try:
            with open(os.path.join(here, name)) as f:
                prev = json.load(f)
            if prev.get("value") is None:  # wrapper records carry no value
                continue
            last_good = {
                "artifact": name,
                "metric": prev.get("metric"),
                "value": prev.get("value"),
                "unit": prev.get("unit"),
                "vs_baseline": prev.get("vs_baseline"),
                "mfu_best": prev.get("mfu_best"),
            }
            break
        except Exception:  # noqa: BLE001 — artifact optional
            continue
    # metric name must match the real series (stage count varies with the
    # environment's device count) — reuse the last good run's name if any
    metric = (last_good or {}).get("metric") or "resnet50_pipeline_throughput"
    print(json.dumps({
        "metric": metric,
        "value": None,
        "unit": "inferences/sec",
        "vs_baseline": None,
        "tpu_unavailable": True,
        "probe_diag": diag,
        "last_good": last_good,
    }))
    sys.stdout.flush()
    sys.stderr.flush()
    # _exit, not sys.exit: a partially-initialized XLA runtime (hung init
    # thread) can block interpreter finalization — the rc=124 mode again
    os._exit(0)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--weights", default=None,
                    help="path to a pretrained ResNet50 checkpoint "
                         "(npz/safetensors; see defer_tpu.utils.pretrained)")
    ap.add_argument("--batches", default="1,32,128,256",
                    help="baseline batch sweep sizes (TPU only)")
    # default sweep covers the best-known configs (r5 winner: chunk=128
    # mb=32 at 11,032 img/s, BENCH_r05_builder.json; r4's default 2x2
    # corners missed the then-winner, under-reporting the pipeline)
    # while every combination stays under the mem_cap guard
    ap.add_argument("--chunks", default="32,128",
                    help="pipeline chunk sweep (steps fused per dispatch)")
    ap.add_argument("--microbatches", default="16,32",
                    help="pipeline microbatch sweep")
    ap.add_argument("--quick", action="store_true",
                    help="small sweep: batches 1,32; one pipeline config")
    ap.add_argument("--fold-bn", action="store_true",
                    help="fold BatchNorm into convs before deployment "
                         "(graph/optimize.py); exact at f32")
    args = ap.parse_args()

    devices = init_devices()


    import jax
    import jax.numpy as jnp

    from defer_tpu import SpmdPipeline, partition, pipeline_mesh
    from defer_tpu.graph.analysis import total_flops
    from defer_tpu.models import resnet50, resnet_tiny, RESNET50_8STAGE_CUTS

    n = len(devices)
    platform = devices[0].platform
    on_tpu = platform != "cpu"
    gen, peak = chip_peak_flops(devices[0])
    log(f"bench: {n} x {platform} device(s)"
        + (f", {gen} ({peak / 1e12:.0f} bf16 TFLOP/s peak)" if on_tpu else ""))

    if on_tpu:
        graph = resnet50()
        in_shape = (224, 224, 3)
        compute_dtype = jnp.bfloat16
        # batch 1 first (the stepwise reference-protocol denominator),
        # then LARGEST first: if the measurement deadline truncates the
        # sweep, the honest scan baseline (big batches) is already in
        batches = [1] + sorted({int(b) for b in args.batches.split(",")}
                               - {1}, reverse=True)
        chunks = [int(c) for c in args.chunks.split(",")]
        mbs = [int(m) for m in args.microbatches.split(",")]
        if args.quick:
            batches, chunks, mbs = [1, 32], [128], [8]
    else:  # CI / local smoke: small model, same code path
        graph = resnet_tiny()
        in_shape = (32, 32, 3)
        compute_dtype = None
        batches = [1]
        chunks, mbs = [8], [1]

    if args.weights and on_tpu:
        from defer_tpu.utils.pretrained import load_pretrained_resnet50
        params = load_pretrained_resnet50(args.weights, graph)
        log(f"bench: loaded pretrained weights from {args.weights}")
    else:
        if args.weights:
            log("bench: --weights ignored on the CPU fallback "
                "(tiny model, random init)")
        params = graph.init(jax.random.key(0))
    if args.fold_bn:
        from defer_tpu import fold_batchnorm
        graph, params, n_folded = fold_batchnorm(graph, params)
        log(f"bench: folded {n_folded} BatchNorm ops into convs")
    # per-sample FLOPs (2*MAC convention) of the graph as DEPLOYED — after
    # any folding, so MFU is scored against the work actually executed
    flops_img = float(total_flops(graph))
    log(f"bench: model FLOPs/img = {flops_img / 1e9:.2f} G")

    # ---- single-chip baseline + batch sweep (test/local_infer.py protocol)
    from defer_tpu.utils.xla_opts import compiler_options, jit_kwargs
    if compiler_options():
        log(f"bench: compiler_options = {compiler_options()}")
    fwd = jax.jit(lambda p, x: graph.apply(p, x), **jit_kwargs())
    # fold_batchnorm and the pretrained loaders return HOST numpy params;
    # device-commit the BASELINE copy once, or every single-chip fwd()
    # call re-ships ~100 MB of weights through the tunnel (measured: 15x
    # slower stepwise, the r5 fold-bn "regression" that wasn't one).
    # `params` itself stays host-side: the pipeline packers np.asarray it.
    if compute_dtype is not None:
        # jnp.asarray casts on device for jax.Arrays and uploads-with-cast
        # for host numpy — no gratuitous D2H either way
        params_c = jax.tree.map(
            lambda a: jnp.asarray(a, dtype=compute_dtype), params)
    else:
        params_c = jax.device_put(params)
    x_dtype = compute_dtype or jnp.float32

    def mfu(ips):
        return round(flops_img * ips / peak, 4) if (on_tpu and peak > 0) \
            else None

    from defer_tpu.utils.profiling import (amortized_forward_seconds,
                                           pipeline_window_seconds,
                                           timed_window)

    def scan_step_seconds(b, k):
        """Per-forward seconds with K forwards fused in ONE dispatch."""
        x0 = jnp.zeros((b,) + in_shape, x_dtype)
        return amortized_forward_seconds(graph.apply, params_c, x0, k)

    # total-measurement deadline: the TPU PROBE is already bounded
    # (VERDICT r4 #1), but a healthy chip with a cold compile cache can
    # still stretch the full sweep past the driver's capture window —
    # past the deadline, remaining sweep items are skipped and the JSON
    # line is emitted with what was measured (ordering above puts the
    # headline configs first)
    bench_deadline = time.monotonic() + float(
        os.environ.get("DEFER_BENCH_DEADLINE_S", "1500"))
    truncated = []

    def past_deadline(what: str) -> bool:
        if time.monotonic() < bench_deadline:
            return False
        if what not in truncated:
            truncated.append(what)
            log(f"bench: measurement deadline reached; skipping "
                f"remaining {what}")
        return True

    sweep = {}
    single_best_ips = 0.0
    for b in batches:
        # truncation is only legal once BOTH the batch-1 stepwise
        # denominator AND the largest-batch scan baseline are in —
        # otherwise vs_baseline would divide by a weak denominator
        # (the r3 weakness-#3 failure mode)
        if len(sweep) >= 2 and past_deadline("batch sweep"):
            break
        xb = jnp.zeros((b,) + in_shape, x_dtype)
        sec = timed_window(lambda: jax.block_until_ready(fwd(params_c, xb)))
        k = 64 if b <= 8 else (32 if b <= 64 else 16)
        scan_sec = scan_step_seconds(b, k)
        entry = {
            "img_per_s": round(b / sec, 2),
            "ms_per_img": round(1e3 * sec / b, 4),
            "ms_per_step": round(1e3 * sec, 4),
            "scan_img_per_s": round(b / scan_sec, 2),
            "scan_ms_per_step": round(1e3 * scan_sec, 4),
        }
        if on_tpu and peak > 0:
            entry["mfu"] = mfu(b / sec)
            entry["scan_mfu"] = mfu(b / scan_sec)
        sweep[b] = entry
        single_best_ips = max(single_best_ips, b / scan_sec)
        log(f"single-chip batch {b}: stepwise {b / sec:.2f} img/s "
            f"({1e3 * sec:.2f} ms/step) | scan x{k} "
            f"{b / scan_sec:.2f} img/s ({1e3 * scan_sec:.3f} ms/step"
            + (f", MFU {entry['scan_mfu']:.1%})" if "scan_mfu" in entry
               else ")"))
    single_stepwise_b1 = sweep[batches[0]]["img_per_s"]

    # ---- pipelined inference over all devices (test/test.py protocol)
    num_stages = n
    if num_stages == 8:
        stages = partition(graph, RESNET50_8STAGE_CUTS if on_tpu else None,
                           num_stages=None if on_tpu else 8)
    else:
        stages = partition(graph, num_stages=num_stages)
    from defer_tpu.partition.stage import buffer_footprint
    buffer_dtype = jnp.bfloat16 if on_tpu else jnp.float32
    buf_elems = buffer_footprint(stages)["buf_elems"]
    mem_cap = 2.5e9  # device bytes allowed for the resident input block

    def bench_pipe(chunk, mb, wire="buffer"):
        """(pipe, img_per_s, sec_per_chunk) with >=2 chunks in flight."""
        pipe = SpmdPipeline(stages, params, mesh=pipeline_mesh(num_stages),
                            microbatch=mb, chunk=chunk,
                            buffer_dtype=buffer_dtype,
                            compute_dtype=compute_dtype, wire=wire)
        inputs = pipe.stage_inputs(
            np.zeros((chunk, mb) + in_shape, np.float32))
        # >=2 chunks in flight, whole-chunk result drains, bubble-free
        # warm-compile (warmup() would cache a SECOND chunk-sized block,
        # doubling the footprint the mem_cap guard accounts for)
        sec = pipeline_window_seconds(pipe, inputs)
        return pipe, chunk * mb / sec, sec

    pipe_sweep = {}
    best = None  # (ips, chunk, mb, pipe)
    # largest in-flight block first: the best-known config (c128/mb32)
    # lands before a deadline truncation can cut the grid short
    for chunk, mb in sorted(((c, m) for c in chunks for m in mbs),
                            key=lambda cm: -(cm[0] * cm[1])):
        if best is not None and past_deadline("pipeline sweep"):
            break
        need = chunk * mb * buf_elems * jnp.dtype(buffer_dtype).itemsize
        if need > mem_cap:
            log(f"pipeline chunk={chunk} mb={mb}: SKIPPED "
                f"(resident input block {need / 1e9:.1f} GB > cap)")
            pipe_sweep[f"c{chunk}_m{mb}"] = {"skipped": "memory"}
            continue
        pipe, ips, sec = bench_pipe(chunk, mb)
        entry = {"img_per_s": round(ips, 2),
                 "ms_per_chunk": round(sec * 1e3, 2),
                 "ms_per_step": round(sec * 1e3 / chunk, 4)}
        if on_tpu and peak > 0:
            entry["mfu"] = mfu(ips)
        pipe_sweep[f"c{chunk}_m{mb}"] = entry
        log(f"pipeline chunk={chunk} mb={mb}: {ips:.2f} img/s"
            + (f" (MFU {entry['mfu']:.1%})" if entry.get("mfu") else ""))
        if best is None or ips > best[0]:
            best = (ips, chunk, mb, pipe)
    if best is None:
        # every swept config hit the memory cap: clamp the smallest one
        # DOWN to the cap (never run over it) so the bench always emits
        # its JSON line without risking the OOM the cap guards against
        mb = min(mbs)
        itemsize = jnp.dtype(buffer_dtype).itemsize
        chunk = max(2, int(mem_cap // (mb * buf_elems * itemsize)))
        log(f"pipeline: all configs over mem cap; clamped to chunk={chunk} "
            f"mb={mb}")
        pipe, ips, _sec = bench_pipe(chunk, mb)
        pipe_sweep[f"c{chunk}_m{mb}"] = {"img_per_s": round(ips, 2),
                                         "forced": True}
        best = (ips, chunk, mb, pipe)
    pipe_ips, best_chunk, best_mb, pipe = best

    # per-stage latency -> duty cycle / bubble metrics on the best config
    try:
        pipe.stage_latencies(iters=3)
    except Exception as e:  # noqa: BLE001 — diagnostics must not kill bench
        log(f"bench: stage_latencies failed: {e!r}")
    deploy_metrics = pipe.metrics.as_dict()

    # ---- int8 wire (the device-side ZFP analogue) on the best config
    int8_row = None
    if on_tpu and not past_deadline("int8 wire diagnostics"):
        try:
            qpipe, q_ips, _ = bench_pipe(best_chunk, best_mb, wire="int8")
            del qpipe  # throughput only; accuracy below on small pipes
            # accuracy: int8 wire vs the bf16 buffer wire actually deployed
            # above AND vs an exact f32 single-program forward, on small
            # dedicated pipes (the big config's run()/flush() would stage
            # another chunk-sized bubble block on device)
            acc = {}
            x_acc = np.random.default_rng(0).standard_normal(
                (4, 1) + in_shape).astype(np.float32)
            y_ref = np.stack([np.asarray(
                fwd(params, jnp.asarray(x)), np.float32) for x in x_acc])
            for w in ("buffer", "int8"):
                p_small = SpmdPipeline(
                    stages, params, mesh=pipeline_mesh(num_stages),
                    microbatch=1, chunk=4, buffer_dtype=buffer_dtype,
                    compute_dtype=compute_dtype, wire=w)
                acc[w] = p_small.run(x_acc)
                del p_small
            denom = max(float(np.abs(y_ref).max()), 1e-6)
            # task-level quality: does the wire change the *decision*?
            # (r4 verdict: a raw logit delta alone can't say whether the
            # quantization matters — top-1/top-5 agreement can)
            ref_top1 = np.argmax(y_ref.reshape(-1, y_ref.shape[-1]), -1)
            ref_top5 = np.argsort(
                y_ref.reshape(-1, y_ref.shape[-1]), -1)[:, -5:]

            def agree(logits):
                flat = np.asarray(logits).reshape(-1, y_ref.shape[-1])
                t1 = float((np.argmax(flat, -1) == ref_top1).mean())
                t5 = float(np.mean([t in row for t, row in
                                    zip(np.argmax(flat, -1), ref_top5)]))
                return t1, t5

            q_t1, q_t5 = agree(acc["int8"])
            b_t1, b_t5 = agree(acc["buffer"])
            int8_row = {
                "img_per_s": round(q_ips, 2),
                "mfu": mfu(q_ips),
                "vs_buffer_wire": round(q_ips / pipe_ips, 4),
                # buffer wire is bf16 on TPU — both deltas are vs the exact
                # f32 single-program logits so they are comparable
                "max_abs_logit_err_vs_f32": round(
                    float(np.abs(acc["int8"] - y_ref).max()), 5),
                "bf16_buffer_max_abs_logit_err_vs_f32": round(
                    float(np.abs(acc["buffer"] - y_ref).max()), 5),
                "rel_logit_err": round(
                    float(np.abs(acc["int8"] - y_ref).max()) / denom, 5),
                "top1_agreement_vs_f32": round(q_t1, 4),
                "top1_in_ref_top5": round(q_t5, 4),
                "bf16_buffer_top1_agreement_vs_f32": round(b_t1, 4),
                "bf16_buffer_top1_in_ref_top5": round(b_t5, 4),
            }
            log(f"pipeline int8 wire: {q_ips:.2f} img/s "
                f"({int8_row['vs_buffer_wire']:.2f}x buffer wire), "
                f"rel logit err {int8_row['rel_logit_err']:.4f} "
                f"(bf16 wire err "
                f"{int8_row['bf16_buffer_max_abs_logit_err_vs_f32']})")
        except Exception as e:  # noqa: BLE001 — optional row
            log(f"bench: int8 wire measurement failed: {e!r}")
            int8_row = {"error": repr(e)[:200]}

    # ---- padded-buffer waste: what each hop actually carries vs buf_elems
    buffer_util = [round(u, 4) for u in pipe.hop_utilization]

    model = "resnet50" if on_tpu else "resnet_tiny"
    result = {
        "metric": f"{model}_{num_stages}stage_pipeline_throughput",
        "value": round(pipe_ips, 3),
        "unit": "inferences/sec",
        # HONEST baseline: the chip's best single-program throughput (scan-
        # amortized, best batch) — r3 divided by the weak batch-1 stepwise
        # number and reported 19.9x; see VERDICT r3 weakness #3
        "vs_baseline": round(pipe_ips / single_best_ips, 4),
        "vs_stepwise_batch1": round(pipe_ips / single_stepwise_b1, 4),
        "single_chip_best_img_per_s": round(single_best_ips, 2),
        "platform": platform,
        "device_kind": str(getattr(devices[0], "device_kind", "")),
        "tpu_generation": gen if on_tpu else None,
        "n_devices": n,
        "compute_dtype": "bfloat16" if compute_dtype is not None else "float32",
        "flops_per_img": flops_img,
        "batch_sweep": {str(k): v for k, v in sweep.items()},
        "pipeline_sweep": pipe_sweep,
        "pipeline_best": {"chunk": best_chunk, "microbatch": best_mb,
                          "img_per_s": round(pipe_ips, 2)},
        "deadline_truncated": truncated or None,
        "deploy_metrics": deploy_metrics,
        "buffer_utilization_per_hop": buffer_util,
        "buffer_elems": pipe.buf_elems,
    }
    if int8_row is not None:
        result["int8_wire"] = int8_row
    if on_tpu and peak > 0:
        result["mfu_pipeline_best"] = mfu(pipe_ips)
        result["mfu_best"] = max(
            [mfu(pipe_ips) or 0.0, mfu(single_best_ips) or 0.0]
            + [v.get("scan_mfu") or 0.0 for v in sweep.values()])
    # telemetry registry snapshot (per-pipeline push/stage latency
    # percentiles, per-hop byte counters) — the bench trajectory's
    # distribution record, not just the window averages above
    from defer_tpu.obs import REGISTRY
    result["metrics_registry"] = REGISTRY.snapshot()
    print(json.dumps(result))


if __name__ == "__main__":
    main()
