"""Headline benchmark: pipelined ResNet50 inference throughput vs. the
single-chip jit baseline.

Mirrors the reference's measurement protocol — timed-window throughput of
batch-1 streaming inference (reference test/test.py:25-37) against a
single-device predict loop (reference test/local_infer.py:16-23) — on
whatever devices are available: N devices → N pipeline stages.

Prints exactly one JSON line on stdout:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}
"""

import json
import os
import sys
import threading
import time

import numpy as np

import jax
import jax.numpy as jnp


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def init_devices():
    """``jax.devices()`` with a wedged-tunnel escape hatch.

    This environment reaches its one TPU chip through a remote PJRT tunnel
    that admits one client at a time; if a previous client died without
    releasing its claim, backend init blocks indefinitely.  Run the init in
    a daemon thread with a timeout and, on timeout, re-exec this script
    pinned to an 8-virtual-device CPU backend so a benchmark line is always
    produced (same code path, smaller model).
    """
    if os.environ.get("DEFER_BENCH_CPU") == "1":
        jax.config.update("jax_platforms", "cpu")
        return jax.devices()

    timeout_s = float(os.environ.get("DEFER_BENCH_TPU_TIMEOUT_S", "600"))
    box = {}

    def _init():
        try:
            box["devices"] = jax.devices()
        except Exception as e:  # noqa: BLE001 — report and fall back
            box["error"] = e

    th = threading.Thread(target=_init, daemon=True)
    th.start()
    th.join(timeout_s)
    if "devices" in box:
        return box["devices"]
    log(f"bench: device init failed ({box.get('error', 'timed out')}); "
        f"re-exec on CPU fallback")
    env = dict(os.environ)
    env["DEFER_BENCH_CPU"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8")
    os.execve(sys.executable, [sys.executable, os.path.abspath(__file__)], env)


def timed_window(fn, *, min_iters=8, min_s=3.0, max_iters=512):
    """Warm call, then measure average seconds/iter over a timed window."""
    fn()  # warmup / compile
    t0 = time.perf_counter()
    n = 0
    while True:
        fn()
        n += 1
        dt = time.perf_counter() - t0
        if (n >= min_iters and dt >= min_s) or n >= max_iters:
            return dt / n


def main():
    from defer_tpu import SpmdPipeline, partition, pipeline_mesh
    from defer_tpu.models import resnet50, resnet_tiny, RESNET50_8STAGE_CUTS

    devices = init_devices()
    n = len(devices)
    platform = devices[0].platform
    on_tpu = platform == "tpu"
    log(f"bench: {n} x {platform} device(s)")

    if on_tpu:
        graph = resnet50()
        in_shape = (224, 224, 3)
        compute_dtype = jnp.bfloat16
        chunk = 32
    else:  # CI / local smoke: small model, same code path
        graph = resnet_tiny()
        in_shape = (32, 32, 3)
        compute_dtype = None
        chunk = 8

    params = graph.init(jax.random.key(0))

    # ---- single-chip baseline (reference test/local_infer.py semantics)
    fwd = jax.jit(lambda p, x: graph.apply(p, x))
    if compute_dtype is not None:
        params_c = jax.tree.map(lambda a: a.astype(compute_dtype), params)
    else:
        params_c = params
    x1 = jnp.zeros((1,) + in_shape,
                   compute_dtype or jnp.float32)
    y = fwd(params_c, x1)
    y.block_until_ready()
    sec = timed_window(lambda: fwd(params_c, x1).block_until_ready())
    single_ips = 1.0 / sec
    log(f"single-chip: {single_ips:.2f} img/s ({sec * 1e3:.3f} ms/img)")

    # ---- pipelined inference over all devices (reference test/test.py)
    num_stages = n
    if on_tpu and num_stages == 8:
        cuts = RESNET50_8STAGE_CUTS  # the reference's exact cut list
        stages = partition(graph, cuts)
    else:
        stages = partition(graph, num_stages=num_stages)
    pipe = SpmdPipeline(stages, params, mesh=pipeline_mesh(num_stages),
                        microbatch=1, chunk=chunk,
                        buffer_dtype=jnp.bfloat16 if on_tpu else jnp.float32,
                        compute_dtype=compute_dtype)
    # pre-stage the input block on device, mirroring the baseline's resident
    # input tensor (the reference harness also re-feeds one image,
    # test/test.py:20-23)
    inputs = pipe.stage_inputs(np.zeros((chunk, 1) + in_shape, np.float32))

    def run_chunk():
        outs = pipe.push(inputs)
        jax.block_until_ready(pipe._a)
        return outs

    pipe.reset()
    sec_chunk = timed_window(run_chunk)
    pipe_ips = chunk / sec_chunk
    log(f"pipeline ({num_stages} stages): {pipe_ips:.2f} img/s "
        f"steady-state, buffer {pipe.buf_elems} elems/hop")

    result = {
        "metric": f"resnet50_{num_stages}stage_pipeline_throughput"
        if on_tpu else f"resnet_tiny_{num_stages}stage_pipeline_throughput",
        "value": round(pipe_ips, 3),
        "unit": "inferences/sec",
        "vs_baseline": round(pipe_ips / single_ips, 4),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
