"""Headline benchmark: ResNet50 inference on TPU — throughput, latency, MFU.

Mirrors the reference's measurement protocol — timed-window throughput of
batch-1 streaming inference (reference test/test.py:25-37) against a
single-device predict loop (reference test/local_infer.py:16-23) — and adds
what the reference never measured: a batch sweep (1/8/32) and model FLOPs
utilisation (graph FLOPs / step time / chip peak).

Device handling: this environment reaches its single TPU chip through a
tunnel that admits one client and can wedge indefinitely if a previous
client died holding the grant.  The TPU is therefore probed in a THROWAWAY
SUBPROCESS (bounded by a timeout) with retries and backoff; only after a
probe succeeds does this process initialize the backend.  Set
``DEFER_BENCH_REQUIRE_TPU=1`` to exit(3) instead of falling back to an
8-virtual-device CPU mesh (same code path, tiny model).

Prints exactly one JSON line on stdout:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ..., extras}
"""

import argparse
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def chip_peak_flops(device) -> tuple[str, float]:
    """(generation, bf16 peak FLOP/s) for ``device``; (unknown, 0) if the
    chip can't be identified — MFU is only reported against a real peak."""
    from defer_tpu.utils.hw import identify_chip, peak_flops
    gen = identify_chip(device)
    return gen, peak_flops(gen)


def probe_tpu_subprocess(timeout_s: float) -> tuple[str | None, str]:
    """Try backend init in a throwaway subprocess; (platform_info, diag).

    The subprocess either prints "platform|device_kind|count" and exits 0,
    or is killed at the timeout — leaving THIS process clean either way
    (an in-process hung init can never be unwound).
    """
    code = (
        "import jax; ds = jax.devices(); "
        "print(ds[0].platform, '|', getattr(ds[0], 'device_kind', ''), "
        "'|', len(ds))"
    )
    t0 = time.perf_counter()
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True,
                           timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return None, f"probe timed out after {timeout_s:.0f}s (tunnel wedged?)"
    dt = time.perf_counter() - t0
    if r.returncode != 0:
        tail = (r.stderr or "").strip().splitlines()[-3:]
        return None, f"probe exited rc={r.returncode} in {dt:.0f}s: {tail}"
    out = (r.stdout or "").strip().splitlines()
    return (out[-1] if out else None), f"probe ok in {dt:.0f}s"


def init_devices():
    """``jax.devices()`` behind a subprocess probe with retries/backoff."""
    if os.environ.get("DEFER_BENCH_CPU") == "1":
        import jax
        jax.config.update("jax_platforms", "cpu")
        return jax.devices()

    # NOTE on the probe-kill tradeoff: killing a probe at its timeout risks
    # leaving a dead client on the single-client tunnel if the probe had
    # already acquired the device grant (it normally hangs *waiting* for
    # it).  There is no graceful way to unwind a C++-level hang, and not
    # probing at all means no TPU number ever; so probe with a generous
    # timeout that comfortably covers a healthy (if slow) init.
    attempts = int(os.environ.get("DEFER_BENCH_TPU_ATTEMPTS", "3"))
    timeout_s = float(os.environ.get("DEFER_BENCH_TPU_TIMEOUT_S", "600"))
    require = os.environ.get("DEFER_BENCH_REQUIRE_TPU") == "1"

    ok = False
    for i in range(attempts):
        info, diag = probe_tpu_subprocess(timeout_s)
        log(f"bench: tpu probe {i + 1}/{attempts}: {diag}"
            + (f" -> {info}" if info else ""))
        if info is not None:
            ok = True
            break
        if i + 1 < attempts:
            backoff = 30.0 * (i + 1)
            log(f"bench: backing off {backoff:.0f}s before retry")
            time.sleep(backoff)

    if ok:
        # the probe released the grant cleanly; init here should be fast —
        # but guard with the same timeout in case the tunnel re-wedged
        box = {}

        def _init():
            try:
                import jax
                box["devices"] = jax.devices()
            except Exception as e:  # noqa: BLE001 — report and fall back
                box["error"] = e

        th = threading.Thread(target=_init, daemon=True)
        th.start()
        th.join(timeout_s)
        if "devices" in box:
            return box["devices"]
        log(f"bench: in-process init failed after successful probe "
            f"({box.get('error', 'timed out')})")

    if require:
        log("bench: DEFER_BENCH_REQUIRE_TPU=1 and no TPU; exiting 3")
        sys.exit(3)
    log("bench: falling back to 8-virtual-device CPU mesh (tiny model); "
        "this is NOT a TPU result")
    env = dict(os.environ)
    env["DEFER_BENCH_CPU"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""  # skip TPU plugin registration entirely
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8")
    os.execve(sys.executable,
              [sys.executable, os.path.abspath(__file__)] + sys.argv[1:], env)


def timed_window(fn, *, min_iters=8, min_s=3.0, max_iters=512):
    """Warm call, then measure average seconds/iter over a timed window."""
    fn()  # warmup / compile
    t0 = time.perf_counter()
    n = 0
    while True:
        fn()
        n += 1
        dt = time.perf_counter() - t0
        if (n >= min_iters and dt >= min_s) or n >= max_iters:
            return dt / n


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--weights", default=None,
                    help="path to a pretrained ResNet50 checkpoint "
                         "(npz/safetensors; see defer_tpu.utils.pretrained)")
    ap.add_argument("--batches", default="1,8,32",
                    help="baseline batch sweep sizes (TPU only)")
    args = ap.parse_args()

    devices = init_devices()

    import jax
    import jax.numpy as jnp

    from defer_tpu import SpmdPipeline, partition, pipeline_mesh
    from defer_tpu.graph.analysis import total_flops
    from defer_tpu.models import resnet50, resnet_tiny, RESNET50_8STAGE_CUTS

    n = len(devices)
    platform = devices[0].platform
    on_tpu = platform != "cpu"
    gen, peak = chip_peak_flops(devices[0])
    log(f"bench: {n} x {platform} device(s)"
        + (f", {gen} ({peak / 1e12:.0f} bf16 TFLOP/s peak)" if on_tpu else ""))

    if on_tpu:
        graph = resnet50()
        in_shape = (224, 224, 3)
        compute_dtype = jnp.bfloat16
        chunk = 32
        # batch 1 always measured: it is the vs_baseline denominator
        batches = sorted({1, *(int(b) for b in args.batches.split(","))})
    else:  # CI / local smoke: small model, same code path
        graph = resnet_tiny()
        in_shape = (32, 32, 3)
        compute_dtype = None
        chunk = 8
        batches = [1]

    if args.weights and on_tpu:
        from defer_tpu.utils.pretrained import load_pretrained_resnet50
        params = load_pretrained_resnet50(args.weights, graph)
        log(f"bench: loaded pretrained weights from {args.weights}")
    else:
        if args.weights:
            log("bench: --weights ignored on the CPU fallback "
                "(tiny model, random init)")
        params = graph.init(jax.random.key(0))
    flops_img = float(total_flops(graph))  # per-sample (2*MAC convention)
    log(f"bench: model FLOPs/img = {flops_img / 1e9:.2f} G")

    # ---- single-chip baseline + batch sweep (test/local_infer.py protocol)
    fwd = jax.jit(lambda p, x: graph.apply(p, x))
    if compute_dtype is not None:
        params_c = jax.tree.map(lambda a: a.astype(compute_dtype), params)
    else:
        params_c = params
    x_dtype = compute_dtype or jnp.float32

    sweep = {}
    for b in batches:
        xb = jnp.zeros((b,) + in_shape, x_dtype)
        sec = timed_window(lambda: jax.block_until_ready(fwd(params_c, xb)))
        ips = b / sec
        entry = {
            "img_per_s": round(ips, 2),
            "ms_per_img": round(1e3 * sec / b, 4),
            "ms_per_step": round(1e3 * sec, 4),
        }
        if on_tpu and peak > 0:
            entry["mfu"] = round(flops_img * ips / peak, 4)
        sweep[b] = entry
        log(f"single-chip batch {b}: {ips:.2f} img/s "
            f"({1e3 * sec / b:.3f} ms/img"
            + (f", MFU {entry['mfu']:.1%})" if "mfu" in entry else ")"))
    single_ips = sweep[1]["img_per_s"]

    # ---- pipelined inference over all devices (test/test.py protocol)
    num_stages = n
    if num_stages == 8:
        stages = partition(graph, RESNET50_8STAGE_CUTS if on_tpu else None,
                           num_stages=None if on_tpu else 8)
    else:
        stages = partition(graph, num_stages=num_stages)
    pipe = SpmdPipeline(stages, params, mesh=pipeline_mesh(num_stages),
                        microbatch=1, chunk=chunk,
                        buffer_dtype=jnp.bfloat16 if on_tpu else jnp.float32,
                        compute_dtype=compute_dtype)
    # pre-stage the input block on device, mirroring the baseline's resident
    # input tensor (the reference harness also re-feeds one image,
    # test/test.py:20-23)
    inputs = pipe.stage_inputs(np.zeros((chunk, 1) + in_shape, np.float32))

    def run_chunk():
        pipe.push(inputs)
        jax.block_until_ready(pipe._a)

    pipe.warmup()
    sec_chunk = timed_window(run_chunk)
    pipe_ips = chunk / sec_chunk
    pipe_mfu = flops_img * pipe_ips / peak if (on_tpu and peak > 0) else None
    log(f"pipeline ({num_stages} stage{'s' if num_stages > 1 else ''}): "
        f"{pipe_ips:.2f} img/s steady-state, buffer {pipe.buf_elems} "
        f"elems/hop" + (f", MFU {pipe_mfu:.1%}" if pipe_mfu else ""))

    model = "resnet50" if on_tpu else "resnet_tiny"
    result = {
        "metric": f"{model}_{num_stages}stage_pipeline_throughput",
        "value": round(pipe_ips, 3),
        "unit": "inferences/sec",
        "vs_baseline": round(pipe_ips / single_ips, 4),
        "platform": platform,
        "device_kind": str(getattr(devices[0], "device_kind", "")),
        "tpu_generation": gen if on_tpu else None,
        "n_devices": n,
        "compute_dtype": "bfloat16" if compute_dtype is not None else "float32",
        "flops_per_img": flops_img,
        "batch_sweep": {str(k): v for k, v in sweep.items()},
    }
    if pipe_mfu is not None:
        result["mfu_pipeline_batch1"] = round(pipe_mfu, 4)
        result["mfu_best"] = max(v.get("mfu", 0.0) for v in sweep.values())
    print(json.dumps(result))


if __name__ == "__main__":
    main()
