"""Trend gate over the append-only benchmark ledger.

``benchmarks/run.py`` appends one JSON row per metric per run to
``BENCH_LEDGER.jsonl`` (successes AND explicit failure rows).  This
script reads that trajectory and flags REGRESSIONS: for every metric,
each successful row is compared against the PREVIOUS successful row of
the same metric, and a drop of more than ``--threshold`` (fraction,
default 0.30) is a regression.  Higher-is-better is assumed — every
ledger metric today is a throughput (inf/s, tokens/sec) or a ratio
where bigger means healthier; a metric whose polarity flips must grow
an entry in ``LOWER_IS_BETTER`` below, not a silent sign hack.

Failure rows (``status: "failed"``) are reported but never compared —
a run that did not measure cannot regress, and the NEXT successful row
is compared against the last successful one, skipping the gap.

Exit codes:
  0  no regressions (including: ledger missing, empty, or every metric
     has fewer than two successful rows — a short history is not a
     failure, it is the absence of a trend)
  1  at least one regression past the threshold

CI runs this warn-only (``continue-on-error``): the ledger in a fresh
checkout is usually absent, and a genuine regression should page a
human via the log, not mask an unrelated PR.

Usage:
  python benchmarks/check_ledger.py
  python benchmarks/check_ledger.py --threshold 0.15 --ledger path.jsonl
"""

from __future__ import annotations

import argparse
import json
import os
import sys

#: metrics where a DROP is an improvement (none today; see module doc)
LOWER_IS_BETTER: frozenset = frozenset()


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def load_rows(path: str) -> list:
    """Parse the JSON-lines ledger, skipping (and counting) unparsable
    lines loudly — a corrupt line must not silently hide the rows
    after it."""
    rows = []
    bad = 0
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rows.append(json.loads(line))
            except json.JSONDecodeError as e:
                bad += 1
                log(f"check_ledger: {path}:{lineno}: unparsable row "
                    f"skipped ({e})")
    if bad:
        log(f"check_ledger: {bad} unparsable line(s) skipped")
    return rows


def check(rows: list, threshold: float) -> list:
    """Return the list of regression records (possibly empty)."""
    last_ok: dict = {}          # metric -> (value, run_unix)
    regressions = []
    for row in rows:
        metric = row.get("metric")
        if metric is None:
            continue
        if row.get("status") == "failed":
            log(f"check_ledger: {metric}: failure row "
                f"({row.get('reason', 'no reason')!r}) — not compared")
            continue
        value = row.get("value")
        if not isinstance(value, (int, float)):
            continue
        prev = last_ok.get(metric)
        last_ok[metric] = (float(value), row.get("run_unix"))
        if prev is None:
            continue
        prev_value, prev_run = prev
        if prev_value == 0:
            continue            # no meaningful ratio against zero
        delta = (float(value) - prev_value) / abs(prev_value)
        if metric in LOWER_IS_BETTER:
            delta = -delta
        if delta < -threshold:
            regressions.append({
                "metric": metric,
                "prev": prev_value,
                "value": float(value),
                "drop_frac": round(-delta, 4),
                "prev_run_unix": prev_run,
                "run_unix": row.get("run_unix"),
            })
    return regressions


def main():
    ap = argparse.ArgumentParser()
    default_ledger = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "BENCH_LEDGER.jsonl")
    ap.add_argument("--ledger", default=default_ledger, metavar="FILE")
    ap.add_argument("--threshold", type=float, default=0.30,
                    help="fractional drop vs the previous successful "
                         "row of the same metric that counts as a "
                         "regression (default 0.30)")
    args = ap.parse_args()

    if not os.path.exists(args.ledger):
        log(f"check_ledger: no ledger at {args.ledger} — nothing to "
            f"gate (fresh checkout?)")
        return 0
    rows = load_rows(args.ledger)
    if not rows:
        log("check_ledger: ledger is empty — nothing to gate")
        return 0

    regressions = check(rows, args.threshold)
    n_metrics = len({r.get("metric") for r in rows
                     if r.get("metric") is not None})
    if not regressions:
        log(f"check_ledger: OK — {len(rows)} row(s) across "
            f"{n_metrics} metric(s), no drop past "
            f"{args.threshold:.0%}")
        return 0
    for r in regressions:
        log(f"check_ledger: REGRESSION {r['metric']}: "
            f"{r['prev']} -> {r['value']} "
            f"(-{r['drop_frac']:.1%}, threshold {args.threshold:.0%})")
    print(json.dumps({"regressions": regressions,
                      "threshold": args.threshold}))
    return 1


if __name__ == "__main__":
    sys.exit(main())
