"""Trend gate over the append-only benchmark ledger.

``benchmarks/run.py`` appends one JSON row per metric per run to
``BENCH_LEDGER.jsonl`` (successes AND explicit failure rows).  This
script reads that trajectory and flags REGRESSIONS: for every metric,
each successful row is compared against the PREVIOUS successful row of
the same metric, and a drop of more than ``--threshold`` (fraction,
default 0.30) is a regression.  Higher-is-better is assumed by default
(throughputs, ratios where bigger means healthier); metrics whose
polarity flips — recovery times, overhead fractions, error fractions —
must grow an entry in ``LOWER_IS_BETTER`` below, not a silent sign
hack.

Failure rows (``status: "failed"``) are reported but never compared —
a run that did not measure cannot regress, and the NEXT successful row
is compared against the last successful one, skipping the gap.

Exit codes:
  0  no regressions (including: ledger missing, empty, or every metric
     has fewer than two successful rows — a short history is not a
     failure, it is the absence of a trend)
  1  at least one regression past the threshold — with ``--fail-on``,
     only regressions on the NAMED metrics flip the exit code (the
     rest stay warnings in the log)

CI runs the all-metrics sweep warn-only (``continue-on-error``): the
ledger in a fresh checkout is usually absent, and a genuine regression
should page a human via the log, not mask an unrelated PR.  On top of
that, ``--fail-on METRIC:PCT`` (repeatable) promotes specific metrics
to build-failing gates at their own per-metric thresholds — CI
enforces ``pipeline_failover`` recovery time and the observability
overhead rows this way, so those regressions fail the build instead of
scrolling past.  ``row=METRIC:PCT`` is accepted as an alias spelling.

Usage:
  python benchmarks/check_ledger.py
  python benchmarks/check_ledger.py --threshold 0.15 --ledger path.jsonl
  python benchmarks/check_ledger.py --fail-on pipeline_failover:1.0 \
      --fail-on obs_overhead:2.0
"""

from __future__ import annotations

import argparse
import json
import os
import sys

#: metrics where a DROP is an improvement: recovery times (ms),
#: instrumentation overhead fractions, and prediction-error fractions —
#: for these an INCREASE is the regression
LOWER_IS_BETTER: frozenset = frozenset({
    "pipeline_failover",      # value = ms recovery
    "obs_overhead",           # value = frac wall overhead vs no trace
    "profile_overhead",       # value = frac wall overhead vs no session
    "blackbox_overhead",      # value = frac wall overhead vs no journal
    "cost_model_truth",       # value = frac abs err of the calibrated
                              # bottleneck prediction
    "request_attribution",    # value = frac residual p99
})


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def load_rows(path: str) -> list:
    """Parse the JSON-lines ledger, skipping (and counting) unparsable
    lines loudly — a corrupt line must not silently hide the rows
    after it."""
    rows = []
    bad = 0
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rows.append(json.loads(line))
            except json.JSONDecodeError as e:
                bad += 1
                log(f"check_ledger: {path}:{lineno}: unparsable row "
                    f"skipped ({e})")
    if bad:
        log(f"check_ledger: {bad} unparsable line(s) skipped")
    return rows


def parse_fail_on(specs: list) -> dict:
    """Parse repeatable ``--fail-on`` specs into ``{metric: frac}``.

    Accepted spellings: ``metric:pct`` and ``row=metric:pct``.  The pct
    is a fraction (``0.5`` = 50%, ``2.0`` = 200% for noisy rows);
    values >= 5 are read as whole percent (``50`` = 0.5) so both
    conventions work without ambiguity.
    """
    enforced: dict = {}
    for spec in specs:
        body = spec[len("row="):] if spec.startswith("row=") else spec
        metric, sep, pct = body.rpartition(":")
        if not sep or not metric:
            raise SystemExit(
                f"check_ledger: bad --fail-on spec {spec!r} "
                f"(want METRIC:PCT, e.g. pipeline_failover:1.0)")
        try:
            frac = float(pct)
        except ValueError:
            raise SystemExit(
                f"check_ledger: bad --fail-on threshold in {spec!r}")
        if frac >= 5.0:
            frac = frac / 100.0
        if frac <= 0:
            raise SystemExit(
                f"check_ledger: --fail-on threshold must be > 0 "
                f"({spec!r})")
        enforced[metric] = frac
    return enforced


def check(rows: list, threshold: float, enforced: dict | None = None) -> list:
    """Return the list of regression records (possibly empty).

    ``enforced`` maps metric -> per-metric threshold fraction; those
    metrics are gated at their own threshold and their regression
    records carry ``enforced: True``.
    """
    enforced = enforced or {}
    last_ok: dict = {}          # metric -> (value, run_unix)
    regressions = []
    for row in rows:
        metric = row.get("metric")
        if metric is None:
            continue
        if row.get("status") == "failed":
            log(f"check_ledger: {metric}: failure row "
                f"({row.get('reason', 'no reason')!r}) — not compared")
            continue
        value = row.get("value")
        if not isinstance(value, (int, float)):
            continue
        prev = last_ok.get(metric)
        last_ok[metric] = (float(value), row.get("run_unix"))
        if prev is None:
            continue
        prev_value, prev_run = prev
        if prev_value == 0:
            continue            # no meaningful ratio against zero
        delta = (float(value) - prev_value) / abs(prev_value)
        if metric in LOWER_IS_BETTER:
            delta = -delta
        gate = enforced.get(metric, threshold)
        if delta < -gate:
            regressions.append({
                "metric": metric,
                "prev": prev_value,
                "value": float(value),
                "drop_frac": round(-delta, 4),
                "threshold": gate,
                "enforced": metric in enforced,
                "prev_run_unix": prev_run,
                "run_unix": row.get("run_unix"),
            })
    return regressions


def main():
    ap = argparse.ArgumentParser()
    default_ledger = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "BENCH_LEDGER.jsonl")
    ap.add_argument("--ledger", default=default_ledger, metavar="FILE")
    ap.add_argument("--threshold", type=float, default=0.30,
                    help="fractional drop vs the previous successful "
                         "row of the same metric that counts as a "
                         "regression (default 0.30)")
    ap.add_argument("--fail-on", action="append", default=[],
                    metavar="METRIC:PCT",
                    help="promote METRIC to a build-failing gate at "
                         "its own threshold (repeatable; fraction, or "
                         "whole percent when >= 5). With any --fail-on "
                         "given, ONLY those metrics flip the exit "
                         "code — others remain log warnings.")
    args = ap.parse_args()
    enforced = parse_fail_on(args.fail_on)

    if not os.path.exists(args.ledger):
        log(f"check_ledger: no ledger at {args.ledger} — nothing to "
            f"gate (fresh checkout?)")
        return 0
    rows = load_rows(args.ledger)
    if not rows:
        log("check_ledger: ledger is empty — nothing to gate")
        return 0

    regressions = check(rows, args.threshold, enforced)
    n_metrics = len({r.get("metric") for r in rows
                     if r.get("metric") is not None})
    if not regressions:
        gates = (f", {len(enforced)} enforced gate(s) clean"
                 if enforced else "")
        log(f"check_ledger: OK — {len(rows)} row(s) across "
            f"{n_metrics} metric(s), no drop past "
            f"{args.threshold:.0%}{gates}")
        return 0
    for r in regressions:
        tag = "REGRESSION" if r["enforced"] or not enforced else "warning"
        log(f"check_ledger: {tag} {r['metric']}: "
            f"{r['prev']} -> {r['value']} "
            f"(-{r['drop_frac']:.1%}, threshold {r['threshold']:.0%})")
    print(json.dumps({"regressions": regressions,
                      "threshold": args.threshold,
                      "fail_on": enforced}))
    if enforced:
        return 1 if any(r["enforced"] for r in regressions) else 0
    return 1


if __name__ == "__main__":
    sys.exit(main())
