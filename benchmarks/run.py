"""Benchmark suite over the five BASELINE.md configs.

Reproduces the reference's measurement protocol per config — timed-window
streaming throughput of the pipelined deployment (reference test/test.py:
25-37) against a single-device predict loop (reference test/local_infer.py:
16-23) — and adds the per-stage metrics the reference never had: stage
latency, duty cycle (energy analogue), bubble fraction.

One JSON line per config on stdout; human detail on stderr.

Usage:
  python benchmarks/run.py                  # all configs, device-appropriate
  python benchmarks/run.py --configs resnet50_8,bert_base_12
  python benchmarks/run.py --tiny           # force tiny models (CPU smoke)
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from defer_tpu import SpmdPipeline, partition, pipeline_mesh  # noqa: E402
from defer_tpu import models  # noqa: E402
from defer_tpu.utils.profiling import (amortized_forward_seconds,  # noqa: E402
                                       pipeline_window_seconds, timed_window)


def log(*a):
    print(*a, file=sys.stderr, flush=True)


#: name -> (full_model_fn, full_cuts, full_in_shape, full_dtype,
#:          tiny_model_fn, tiny_stages, tiny_in_shape, tiny_dtype)
CONFIGS = {
    "resnet50_8": (
        models.resnet50, models.RESNET50_8STAGE_CUTS, (224, 224, 3), "f",
        models.resnet_tiny, 8, (32, 32, 3), "f"),
    "vgg19_4": (
        models.vgg19, models.VGG19_4STAGE_CUTS, (224, 224, 3), "f",
        models.vgg_tiny, 4, (32, 32, 3), "f"),
    "inceptionv3_6": (
        models.inception_v3, models.INCEPTION_6STAGE_CUTS, (299, 299, 3), "f",
        models.inception_tiny, 6, (75, 75, 3), "f"),
    "mobilenetv2_2": (
        models.mobilenet_v2, models.MOBILENETV2_2STAGE_CUTS, (224, 224, 3),
        "f", models.mobilenet_tiny, 2, (32, 32, 3), "f"),
    "bert_base_12": (
        models.bert_base, models.BERT_BASE_12STAGE_CUTS, (128,), "i",
        models.bert_tiny, 4, (16,), "i"),
}


def sample(shape, kind, microbatch, lead=()):
    full = lead + (microbatch,) + shape
    if kind == "i":
        return (np.arange(int(np.prod(full))).reshape(full) % 100
                ).astype(np.float32)
    return np.zeros(full, np.float32)


#: config name -> (pretrained-loader family, checkpoint basename)
PRETRAINED = {
    "resnet50_8": "resnet50",
    "vgg19_4": "vgg19",
    "inceptionv3_6": "inception_v3",
    "mobilenetv2_2": "mobilenet_v2",
    "bert_base_12": "bert_base",
}


def _load_weights(name: str, graph, weights_dir: str | None):
    """Trained weights for a full config when a checkpoint is present
    (reference parity: it benchmarks ResNet50(weights="imagenet"),
    test/test.py:13-14).  Returns (params, trained?)."""
    family = PRETRAINED.get(name)
    if weights_dir and family:
        import os
        from defer_tpu.utils.pretrained import load_pretrained
        for ext in (".pt", ".pth", ".npz", ".safetensors", ".bin"):
            p = os.path.join(weights_dir, family + ext)
            if os.path.exists(p):
                log(f"{name}: loading trained weights {p}")
                return load_pretrained(family, p, graph), True
        log(f"{name}: no {family}.* checkpoint in {weights_dir}; "
            f"random init")
    return graph.init(jax.random.key(0)), False


def run_config(name, *, tiny: bool, chunk: int, stage_lat: bool,
               microbatch: int = 1, force_full: bool = False,
               weights_dir: str | None = None):
    (full_fn, full_cuts, full_shape, full_kind,
     tiny_fn, tiny_stages, tiny_shape, tiny_kind) = CONFIGS[name]
    on_tpu = jax.default_backend() == "tpu"
    use_full = (on_tpu or force_full) and not tiny
    n_dev = len(jax.devices())

    if use_full:
        graph, in_shape, kind = full_fn(), full_shape, full_kind
        cuts, num_stages = full_cuts, None
        want = len(full_cuts) + 1
    else:
        graph, in_shape, kind = tiny_fn(), tiny_shape, tiny_kind
        cuts, num_stages = None, min(tiny_stages, n_dev)
        want = num_stages
    if want > n_dev:
        cuts, num_stages, want = None, n_dev, n_dev
        log(f"{name}: only {n_dev} devices; auto-partitioning to {n_dev}")

    params, trained = _load_weights(name, graph,
                                    weights_dir if use_full else None)
    compute_dtype = jnp.bfloat16 if on_tpu and kind == "f" else None

    # single-device baseline (reference test/local_infer.py semantics),
    # reported stepwise (dispatch+sync per predict, reference protocol)
    # AND scan-amortized (K forwards in ONE dispatch — the chip's true
    # best; the honest vs_baseline denominator, VERDICT r3 weakness #3)
    x1 = jnp.asarray(sample(in_shape, kind, microbatch))
    if kind == "i":
        x1 = x1.astype(jnp.int32)
    elif compute_dtype is not None:
        # baseline must compute in the same dtype as the pipeline: f32
        # inputs would make every op cast params back up, timing an f32
        # baseline against a bf16 pipeline (inflating vs_baseline)
        x1 = x1.astype(compute_dtype)
    fwd = jax.jit(graph.apply)
    # device-commit the BASELINE copy once (pretrained loaders return
    # host numpy; per-call jit re-upload through the tunnel would make
    # the baseline ~15x slower — the r5 fold-bn lesson).  `params`
    # stays host-side for SpmdPipeline's packer.
    params_c = (jax.tree.map(lambda a: jnp.asarray(a, dtype=compute_dtype),
                             params)
                if compute_dtype else jax.device_put(params))
    base_step_s = timed_window(
        lambda: jax.block_until_ready(fwd(params_c, x1)),
        min_s=2.0, max_iters=256) / microbatch
    base_s = amortized_forward_seconds(
        graph.apply, params_c, x1, 32 if on_tpu else 8) / microbatch

    stages = partition(graph, cuts, num_stages=num_stages)
    pipe = SpmdPipeline(
        stages, params, mesh=pipeline_mesh(len(stages)),
        microbatch=microbatch, chunk=chunk,
        buffer_dtype=jnp.bfloat16 if on_tpu and kind == "f" else jnp.float32,
        compute_dtype=compute_dtype)
    xs = pipe.stage_inputs(sample(in_shape, kind, microbatch, lead=(chunk,)))
    pipe_s = pipeline_window_seconds(pipe, xs) / chunk / microbatch
    lats = None
    if stage_lat:
        lats = pipe.stage_latencies()

    from defer_tpu.graph.analysis import total_flops
    from defer_tpu.utils.hw import (analytic_pipeline_model, ici_bandwidth,
                                    identify_chip, peak_flops)

    m = pipe.metrics.as_dict()
    result = {
        "metric": f"{name}{'_tiny' if not use_full else ''}_throughput",
        "value": round(1.0 / pipe_s, 3),
        "unit": "inferences/sec",
        # honest: vs the scan-amortized single-device forward
        "vs_baseline": round(base_s / pipe_s, 4),
        "vs_stepwise_baseline": round(base_step_s / pipe_s, 4),
        "stages": len(stages),
        "trained_weights": trained,
        "microbatch": microbatch,
        "chunk": chunk,
        "single_device_s": round(base_s, 6),
        "single_device_stepwise_s": round(base_step_s, 6),
        "stage_latency_ms": m["stage_latency_ms"],
        # latency *distributions* (telemetry PR): per-chunk push and
        # per-stage percentiles, so BENCH_*.json rows carry p50/p95/p99
        "push_latency_ms": m.get("push_latency_ms"),
        "stage_latency_percentiles_ms": m.get(
            "stage_latency_percentiles_ms"),
        "duty_cycle": m["duty_cycle"],
        "pipeline_efficiency": m["pipeline_efficiency"],
        "bubble_fraction": m["bubble_fraction"],
        "buffer_bytes_per_hop": m["buffer_bytes_per_hop"],
        # padded-buffer waste per hop: what each stage boundary actually
        # carries vs the homogeneous buf_elems every hop pays
        "buffer_elems": pipe.buf_elems,
        "buffer_utilization_per_hop": [
            round(u, 4) for u in pipe.hop_utilization],
        "buffer_utilization_mean": round(
            sum(pipe.hop_utilization) / len(pipe.hop_utilization), 4),
    }
    gen = identify_chip(jax.devices()[0])
    peak = peak_flops(gen) if on_tpu else 0.0
    if peak > 0:
        # the pipeline spans len(stages) chips: utilization is against the
        # aggregate peak, not one chip's
        result["mfu"] = round(
            float(total_flops(graph)) / pipe_s / (peak * len(stages)), 4)
    if lats:
        # the written multi-chip argument: what an N-chip pipeline of these
        # measured stages would do, and where it loses vs ideal N
        result["analytic"] = analytic_pipeline_model(
            lats, m["buffer_bytes_per_hop"],
            ici_bandwidth(gen) if on_tpu else 0.0)

    if use_full and len(stages) < len(full_cuts) + 1 and stage_lat:
        # only 1 chip, but the full N-stage partition's per-stage story is
        # still measurable: time each stage's compiled branch standalone
        # (scan-amortized) and feed the analytic pipeline model — the
        # checkable multi-chip claim per config (BASELINE.md target)
        full = partition(graph, full_cuts)
        full_ms = []
        for s in full:
            sp = s.select_params(params_c)
            is_int = jnp.issubdtype(s.in_spec.dtype, jnp.integer)
            x = jnp.asarray(sample(s.in_spec.shape, "i" if is_int else "f",
                                   microbatch))
            if is_int:
                x = x.astype(jnp.int32)
            elif compute_dtype is not None:
                x = x.astype(compute_dtype)
            sec = amortized_forward_seconds(
                lambda p, xx, _s=s: _s.fn(p, xx), sp, x,
                16 if on_tpu else 4, min_s=1.0, max_iters=16)
            full_ms.append(sec * 1e3)
        from defer_tpu.partition.stage import buffer_footprint
        fp = buffer_footprint(
            full, microbatch=microbatch,
            itemsize=2 if on_tpu and kind == "f" else 4)
        result["full_partition"] = {
            "stages": len(full),
            "stage_ms": [round(v, 4) for v in full_ms],
            "buffer_elems": fp["buf_elems"],
            "buffer_utilization_per_hop": [
                round(u, 4) for u in fp["hop_utilization"]],
            "analytic": analytic_pipeline_model(
                [v / 1e3 for v in full_ms], fp["bytes_per_hop"],
                ici_bandwidth(gen) if on_tpu else 0.0),
        }
    return result


def run_script_row(script_name: str, extra_argv: list | None = None):
    """Delegate a row to a standalone smoke script in a subprocess (its
    CPU-pinned child environment must never touch this process's
    backend).  Returns the script's JSON row (last stdout line)."""
    import os
    import subprocess
    script = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", script_name)
    # pin the child to CPU explicitly: the scripts' own setdefault is a
    # no-op when a TPU host inherits JAX_PLATFORMS/PALLAS_AXON_POOL_IPS,
    # and the tunnel admits exactly one client (held by this process)
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PALLAS_AXON_POOL_IPS": "",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=1"}
    proc = subprocess.run([sys.executable, script] + (extra_argv or []),
                          capture_output=True,
                          text=True, timeout=900, env=env)
    if proc.returncode != 0:
        raise RuntimeError(
            f"{script_name} rc={proc.returncode}: {proc.stderr[-2000:]}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


#: script-delegated rows: `chain_overlap` (multi-process localhost chain,
#: overlapped vs serial node loop), `plan_vs_quantile` (bottleneck-
#: solver cuts vs greedy quantile cuts, predicted + measured — the row
#: reports how much the quantile baseline loses on the skewed chain),
#: `stage_replication` (hybrid pipeline/data-parallel chain: R=2 replicas
#: of a delay-bottlenecked stage vs the serial chain — byte-identical
#: outputs, >= 1.5x measured throughput, solver tie-in) and
#: `obs_overhead` (live observability plane: monitor rows converge to
#: node stats, bottleneck + straggler + replan name the delay-bound
#: stage, clock-aligned waterfalls, telemetry wall overhead < 5%) and
#: `colocated_fastpath` (transport tiers: colocated chain — one OS
#: process, local in-memory hops negotiated by the tier_probe handshake
#: — byte-identical to the all-TCP chain and >= 1.5x faster on a
#: codec-delay-bound chain; fused device hops eliminate the inter-stage
#: frame entirely; rows record the NEGOTIATED tier per hop so BENCH_*
#: trajectories distinguish TCP-bound from colocated/fused runs)
#: ... and `serving_frontdoor` (multi-tenant front door over one
#: deployed chain: >= 3 concurrent tenant streams byte-identical to
#: solo runs, continuous batching >= 1.5x sequential one-stream-at-a-
#: time serving on the delay-bound chain, and SLO-aware shedding
#: holding admitted p99 inside the SLO under a 2x-overload burst of a
#: deterministic OPEN-LOOP Poisson arrival trace — closed-loop load
#: hides queueing delay, so the p99 here is measured against arrivals
#: fixed up front; `--arrival-seed` reseeds the trace)
#: ... and `dag_pipeline` (branch-parallel stage graphs: the two-branch
#: delay-bound inception_tiny region deployed as concurrent sub-
#: pipelines between a broadcast fork and an all-paths (path, seq)
#: join, byte-identical to the serial composition of its own stage
#: programs and >= 1.5x min-of-3 wall vs the best linear-cut chain at
#: the SAME node count; the row also records the critical-path
#: planner's predicted DAG-vs-linear bottlenecks on inception_tiny and
#: the branched MoE family — docs/PLANNER.md)
#: ... and `shm_fastpath` (shared-memory transport tier: the same
#: codec-delay-bound 3-stage chain as REAL OS processes with every hop
#: — dispatcher edges included — negotiated `shm` via the tier_probe
#: handshake: activations cross a shared-memory ring while the socket
#: is demoted to a doorbell; byte-identical to the all-TCP chain,
#: >= 1.5x measured min-of-3 streams, zero codec.* samples on every
#: stage's live channels, and no /dev/shm segment survives teardown —
#: the same-host cross-PROCESS rung the colocated_fastpath row's
#: `local` tier cannot reach)
#: ... and `ici_fastpath` (device-resident transport tier: a copy-bound
#: fat-activation 3-stage chain on a FORCED 4-device host mesh, every
#: hop incl. dispatcher edges negotiated `ici` — live jax.Arrays cross
#: the hops with ZERO host materialization (zero codec.* AND zero
#: host_sync samples asserted; the one host sync per frame happens at
#: the dispatcher's result edge) and the thin cross-device hop performs
#: a real device-to-device jax.device_put per frame (distinct src/dst
#: device ids asserted from stats); byte-identical to all-tcp /
#: all-shm / all-local, >= 1.3x min-of-3 vs all-shm — the two REAL
#: memcpys per hop per frame the device-resident path eliminates; the
#: local tier is reported too but jax CPU host interop is zero-copy
#: both ways, so ici ~= local on this vehicle by design)
#: ... and `cost_model_truth` (the cost-model truth loop: calibrate
#: CalibratedConstants — host-sync / wire bandwidths, per-deployed-
#: codec throughputs — from a no-delay chain's own telemetry, then
#: assert the CALIBRATED model predicts the codec-delay-bound chain's
#: bottleneck stage service within 15% where the default model —
#: which prices the unknown dsleep/esleep codecs as raw — is
#: measurably worse; an injected slowdown must fire a `model_drift`
#: flight-recorder event within 2 monitor intervals; telemetry
#: overhead stays < 5% on the interleaved min-of-3 protocol; the row
#: embeds the fitted constants so BENCH_LEDGER.jsonl carries the
#: calibration trajectory — docs/PLANNER.md "calibrated constants")
#: ... and `request_attribution` (request-scoped serving
#: observability: under the serving row's 2x-burst open-loop trace,
#: the p50 AND p99 sampled requests' attributed budget buckets —
#: admission + batch-gather + per-stage compute + per-hop transport +
#: result edge, folded from the request's clock-aligned spans by
#: obs/attrib.py — sum to within 10% of each request's measured
#: end-to-end latency; the flight recorder's merged event log carries
#: the burst's shed and straggler events in per-process seq order with
#: zero ring drops at default capacity; and recorder+tracing overhead
#: stays < 5% vs telemetry-off on the interleaved min-of-3 protocol
#: obs_overhead established)
#: ... and `pipeline_failover` (the seq-replay substrate's chaos row:
#: kill -9 a mid-chain stage-1 replica while the stream is in flight —
#: the supervisor respawns it, the upstream fan-out heals and replays
#: its unacked window, and the run must end byte-identical to an
#: undisturbed reference; the row's value is the healed hop's measured
#: recovery wall time (ms) from its `failover` flight-recorder event,
#: and the same row carries the zero-downtime live-replan leg: a
#: mid-stream quiesce -> redeploy -> resume cutover onto the same
#: persist processes, byte-identical with its cutover_ms —
#: docs/ROBUSTNESS.md)
#: ... and `decode_profile` (the decode steady-state X-ray: after one
#: warmup generate, a second identical generate must reach XLA ZERO
#: times — measured by the jax.monitoring compile listener — with
#: EXACTLY ceil(num_steps/chunk_steps) scan dispatches and a dispatch
#: share <= ~1 of the generation wall; the guard rail under the mb64
#: decode-cliff autopsy in docs/DECODE_CLIFF.md)
#: ... and `blackbox_overhead` (the flight-recorder black box: the
#: chaos row's kill -9 replayed with --journal-dir on every process,
#: then the postmortem re-assembled OFFLINE from nothing but the
#: on-disk journals — verdict must name the killed replica with
#: journal-stop evidence, rank the nearest downstream stage first
#: among casualties, and show no negative inter-process gap on the
#: anchor-aligned timeline; the row's value is the journaling wall
#: tax from the interleaved min-of-3 on/off protocol, asserted < 5% —
#: docs/OBSERVABILITY.md "Black box & postmortem")
SCRIPT_ROWS = {
    "chain_overlap": "chain_overlap_smoke.py",
    "pipeline_failover": "chaos_smoke.py",
    "ici_fastpath": "ici_smoke.py",
    "plan_vs_quantile": "plan_smoke.py",
    "stage_replication": "replication_smoke.py",
    "obs_overhead": "monitor_smoke.py",
    "colocated_fastpath": "colocate_smoke.py",
    "shm_fastpath": "shm_smoke.py",
    "serving_frontdoor": "serve_smoke.py",
    "request_attribution": "request_obs_smoke.py",
    "dag_pipeline": "dag_smoke.py",
    "cost_model_truth": "capacity_smoke.py",
    "decode_profile": "decode_profile_smoke.py",
    "blackbox_overhead": "postmortem_smoke.py",
}


def ledger_append(path: str, row: dict):
    """Append one row to the machine-readable benchmark ledger
    (JSON-lines, one object per line, append-only — the cross-run
    trajectory BENCH_*.json snapshots cannot give).  Every row — config
    results, script rows, AND failures — lands here with a wall-clock
    stamp, so a probed-down row is an explicit record with a reason
    field, not a silent omission."""
    if not path:
        return
    try:
        with open(path, "a") as f:
            f.write(json.dumps(row, sort_keys=True) + "\n")
    except OSError as e:
        log(f"ledger: cannot append to {path}: {e!r}")


def failure_row(name: str, exc: Exception, *, kind: str,
                elapsed_s: float) -> dict:
    """An explicit machine-readable failure row: the metric that did
    NOT get measured and why.  `reason` carries the exception text
    (e.g. a smoke script's rc/stderr tail), `row_kind` whether it was
    a script-delegated probe or an in-process config."""
    return {
        "metric": name,
        "status": "failed",
        "row_kind": kind,
        "reason": f"{type(exc).__name__}: {exc}",
        "elapsed_s": round(elapsed_s, 1),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--configs", default=",".join(CONFIGS)
                    + "," + ",".join(SCRIPT_ROWS))
    ap.add_argument("--tiny", action="store_true",
                    help="force tiny variants (CPU smoke)")
    ap.add_argument("--full", action="store_true",
                    help="force full models even off-TPU (slow)")
    ap.add_argument("--chunk", type=int, default=0,
                    help="steps fused per dispatch (0 = 128 on TPU, 16 off)")
    ap.add_argument("--microbatch", type=int, default=1)
    ap.add_argument("--no-stage-latency", action="store_true")
    ap.add_argument("--weights-dir", default=None,
                    help="directory of trained checkpoints "
                         "(resnet50.pt, vgg19.pt, mobilenet_v2.pt, ...)")
    ap.add_argument("--arrival-seed", type=int, default=None,
                    help="reseed the serving row's open-loop arrival "
                         "trace (deterministic Poisson + 2x burst; "
                         "defaults to the smoke's built-in seed)")
    import os
    default_ledger = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "BENCH_LEDGER.jsonl")
    ap.add_argument("--ledger", default=default_ledger, metavar="FILE",
                    help="append every row (successes AND explicit "
                         "failure rows) to this JSON-lines ledger "
                         "('' disables)")
    args = ap.parse_args()

    run_unix = time.time()
    backend = jax.default_backend()

    def emit(row: dict):
        row = {**row, "run_unix": round(run_unix, 1), "backend": backend}
        print(json.dumps(row), flush=True)
        ledger_append(args.ledger, row)

    chunk = args.chunk or (128 if backend == "tpu" else 16)
    for name in args.configs.split(","):
        name = name.strip()
        if name in SCRIPT_ROWS:
            t0 = time.time()
            extra = []
            if name in ("serving_frontdoor", "request_attribution") \
                    and args.arrival_seed is not None:
                extra = ["--seed", str(args.arrival_seed)]
            try:
                r = run_script_row(SCRIPT_ROWS[name], extra)
            except Exception as e:  # noqa: BLE001 — keep the suite going
                log(f"{name}: FAILED {type(e).__name__}: {e}")
                emit(failure_row(name, e, kind="script",
                                 elapsed_s=time.time() - t0))
                continue
            log(f"{name}: {r['value']}x ({r['unit']}, "
                f"{time.time() - t0:.0f}s)")
            emit(r)
            continue
        if name not in CONFIGS:
            log(f"unknown config {name!r}; have {list(CONFIGS)}")
            emit({"metric": name, "status": "failed",
                  "row_kind": "config",
                  "reason": f"unknown config; have "
                            f"{sorted(list(CONFIGS) + list(SCRIPT_ROWS))}"})
            continue
        t0 = time.time()
        try:
            r = run_config(name, tiny=args.tiny, chunk=chunk,
                           microbatch=args.microbatch,
                           stage_lat=not args.no_stage_latency,
                           force_full=args.full,
                           weights_dir=args.weights_dir)
        except Exception as e:  # noqa: BLE001 — keep the suite going
            log(f"{name}: FAILED {type(e).__name__}: {e}")
            emit(failure_row(name, e, kind="config",
                             elapsed_s=time.time() - t0))
            continue
        log(f"{name}: {r['value']} inf/s ({time.time() - t0:.0f}s)")
        emit(r)


if __name__ == "__main__":
    main()
