"""defer_tpu — TPU-native distributed pipelined DNN inference.

A ground-up JAX/XLA re-design of the capabilities of ANRGUSC/DEFER
(arXiv:2201.06769): partition a model DAG into N sequential stages, place
stage i on device i of a TPU mesh, and stream inference inputs through the
chain with every stage concurrently busy.  The reference's TCP relay chain
becomes a single SPMD program (``shard_map`` + ``lax.ppermute`` over ICI);
its ZFP/LZ4 wire codec becomes bfloat16 HBM-resident buffers.

Quick start::

    import defer_tpu as dt

    graph = dt.models.resnet50()
    params = graph.init(jax.random.key(0))
    defer = dt.Defer(config=dt.DeferConfig(microbatch=1, chunk=16))
    outputs = defer.run(graph, params, inputs, num_stages=8)
"""

from . import models
from . import plan
from .graph.analysis import (auto_cut_points, max_activation_bytes,
                             total_flops, valid_cut_points)
from .graph.ir import GraphBuilder, LayerGraph, Op, ShapeSpec
from .graph.optimize import fold_batchnorm
from .graph.viz import summary, to_dot
from .ops import flash_attention
from .codec import (BlockFloatCodec, Codec, LosslessCodec, PipelineCodec,
                    RawCodec)
from .parallel.mesh import DATA_AXIS, STAGE_AXIS, pipeline_mesh
from .parallel.ring_attention import (SEQ_AXIS, ring_attention,
                                      sequence_parallel_attention)
from .parallel.ulysses import (sequence_parallel_attention_ulysses,
                               ulysses_attention)
from .parallel.distributed import (initialize, multihost_pipeline_mesh,
                                   process_local_batch)
from .parallel.expert import (EXPERT_AXIS, expert_parallel_fn,
                              expert_parallel_mesh, shard_moe_params)
from .parallel.tensor import (MODEL_AXIS, shard_tp_params,
                              tensor_parallel_fn, tensor_parallel_mesh)
from .partition.partitioner import partition
from .partition.stage import StageSpec
from .runtime.decode import PipelinedDecoder
from .runtime.dispatcher import Defer, DeferHandle, END_OF_STREAM
from .runtime.speculative import speculative_generate
from .runtime.mpmd import MpmdPipeline
from .runtime.spmd import SpmdPipeline
from .runtime.training import PipelineTrainer
from .utils.checkpoint import load_params, save_params
from .utils.export import export_pipeline, export_stage, load_stage
from .utils.config import DeferConfig
from .obs import (LatencyHistogram, MetricsRegistry, REGISTRY,
                  enable_tracing, export_chrome_trace, get_registry, tracer)
from .utils.metrics import PipelineMetrics, StopwatchWindow
from .utils.profiling import profile_pipeline, trace

__version__ = "0.1.0"

__all__ = [
    "GraphBuilder", "LayerGraph", "Op", "ShapeSpec", "StageSpec",
    "partition", "valid_cut_points", "auto_cut_points", "total_flops",
    "max_activation_bytes", "plan",
    "fold_batchnorm",
    "summary", "to_dot",
    "pipeline_mesh", "STAGE_AXIS", "DATA_AXIS",
    "SpmdPipeline", "MpmdPipeline", "PipelineTrainer", "PipelinedDecoder",
    "speculative_generate",
    "Defer", "DeferHandle", "DeferConfig",
    "END_OF_STREAM", "PipelineMetrics", "StopwatchWindow", "models",
    "SEQ_AXIS", "ring_attention", "sequence_parallel_attention",
    "sequence_parallel_attention_ulysses", "ulysses_attention",
    "flash_attention",
    "MODEL_AXIS", "shard_tp_params", "tensor_parallel_fn",
    "tensor_parallel_mesh",
    "EXPERT_AXIS", "expert_parallel_fn", "expert_parallel_mesh",
    "shard_moe_params",
    "initialize", "multihost_pipeline_mesh", "process_local_batch",
    "Codec", "BlockFloatCodec", "LosslessCodec", "PipelineCodec", "RawCodec",
    "save_params", "load_params", "profile_pipeline", "trace",
    "export_stage", "export_pipeline", "load_stage",
    "LatencyHistogram", "MetricsRegistry", "REGISTRY", "get_registry",
    "tracer", "enable_tracing", "export_chrome_trace",
]
