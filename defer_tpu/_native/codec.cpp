// defer_tpu native host-side codec.
//
// TPU-native answer to the reference's third-party native compression deps
// (zfpy/ZFP and lz4.frame — reference src/dispatcher.py:81-84,
// src/node.py:76-79): on-pod transfers never touch this (activations stay in
// HBM and ride ICI), but the host/DCN edge still wants a real codec for
// streaming ingest/egress and weight shipping.  Two first-party codecs:
//
//  1. blockfloat: fixed-rate lossy float codec in the spirit of ZFP's
//     fixed-rate mode — blocks of 64 floats share one exponent byte, each
//     value stores a signed fixed-point mantissa of `bits` bits.  Rate and
//     error are strictly bounded, compression is branch-free and
//     vectorizable.
//  2. lzb: LZ77 byte compressor (greedy hash-chain match, 64KB window,
//     varint-framed literals/matches) layered over blockfloat the way LZ4
//     was layered over ZFP.  Self-describing frame, first-party format.
//
// C ABI only (ctypes-friendly).  Build: see Makefile in this directory.

#include <cstdint>
#include <cstring>
#include <cmath>
#include <algorithm>

extern "C" {

// ---------------------------------------------------------------------------
// blockfloat: shared-exponent fixed-rate float codec
// ---------------------------------------------------------------------------

static const int BF_BLOCK = 64;

// bytes needed for n floats at `bits` mantissa bits per value
int64_t bf_max_compressed_size(int64_t n, int bits) {
  int64_t nblocks = (n + BF_BLOCK - 1) / BF_BLOCK;
  int64_t payload = (static_cast<int64_t>(BF_BLOCK) * bits + 7) / 8;
  return 16 + nblocks * (1 + payload);  // header: magic, n, bits
}

// Compress n floats -> dst.  Returns bytes written, or -1 on error.
int64_t bf_compress(const float* src, int64_t n, int bits, uint8_t* dst) {
  if (bits < 2 || bits > 24 || n < 0) return -1;
  uint8_t* out = dst;
  std::memcpy(out, "BFC1", 4); out += 4;
  std::memcpy(out, &n, 8); out += 8;
  *out++ = static_cast<uint8_t>(bits);
  *out++ = 0; *out++ = 0; *out++ = 0;  // pad header to 16

  const int64_t nblocks = (n + BF_BLOCK - 1) / BF_BLOCK;
  const int32_t qmax = (1 << (bits - 1)) - 1;
  for (int64_t b = 0; b < nblocks; ++b) {
    const int64_t lo = b * BF_BLOCK;
    const int64_t hi = std::min(lo + BF_BLOCK, n);
    // shared exponent = exponent of the largest magnitude in the block
    float amax = 0.f;
    for (int64_t i = lo; i < hi; ++i) {
      float a = std::fabs(src[i]);
      if (std::isfinite(a) && a > amax) amax = a;
    }
    int e = 0;
    if (amax > 0.f) std::frexp(amax, &e);  // amax = m * 2^e, m in [0.5, 1)
    // clamp so the biased byte can't wrap: |x| >= 2^127 saturates toward
    // 2^127, subnormal blocks flush toward 0 (both backends identical)
    e = std::max(-127, std::min(127, e));
    *out++ = static_cast<uint8_t>(e + 128);
    // double: 2^127 * qmax overflows float, and lround(inf) would be UB
    const double scale = std::ldexp(1.0, -e) * qmax;  // value -> fixed point
    // pack mantissas little-endian bit stream
    uint64_t acc = 0;
    int nbits = 0;
    for (int64_t i = lo; i < lo + BF_BLOCK; ++i) {
      float v = (i < hi && std::isfinite(src[i])) ? src[i] : 0.f;
      int32_t q = static_cast<int32_t>(std::lround(v * scale));
      q = std::max(-qmax, std::min(qmax, q));
      uint32_t u = static_cast<uint32_t>(q + qmax);  // bias to unsigned
      acc |= static_cast<uint64_t>(u) << nbits;
      nbits += bits;
      while (nbits >= 8) {
        *out++ = static_cast<uint8_t>(acc & 0xff);
        acc >>= 8;
        nbits -= 8;
      }
    }
    if (nbits > 0) *out++ = static_cast<uint8_t>(acc & 0xff);
  }
  return out - dst;
}

// Decompress -> dst (must hold n floats; n returned via bf_peek_count).
// Returns number of floats written, or -1 on malformed input.
int64_t bf_decompress(const uint8_t* src, int64_t src_len, float* dst) {
  if (src_len < 16 || std::memcmp(src, "BFC1", 4) != 0) return -1;
  int64_t n;
  std::memcpy(&n, src + 4, 8);
  const int bits = src[12];
  if (bits < 2 || bits > 24 || n < 0) return -1;
  const uint8_t* in = src + 16;
  const uint8_t* end = src + src_len;
  const int64_t nblocks = (n + BF_BLOCK - 1) / BF_BLOCK;
  const int32_t qmax = (1 << (bits - 1)) - 1;
  const int64_t payload = (static_cast<int64_t>(BF_BLOCK) * bits + 7) / 8;
  for (int64_t b = 0; b < nblocks; ++b) {
    if (in + 1 + payload > end) return -1;
    const int e = static_cast<int>(*in++) - 128;
    const double inv = std::ldexp(1.0, e) / qmax;
    uint64_t acc = 0;
    int nbits = 0;
    const int64_t lo = b * BF_BLOCK;
    for (int64_t i = lo; i < lo + BF_BLOCK; ++i) {
      while (nbits < bits) {
        acc |= static_cast<uint64_t>(*in++) << nbits;
        nbits += 8;
      }
      uint32_t u = static_cast<uint32_t>(acc & ((1u << bits) - 1));
      acc >>= bits;
      nbits -= bits;
      if (i < n) dst[i] = static_cast<float>(
          (static_cast<int32_t>(u) - qmax) * inv);
    }
  }
  return n;
}

int64_t bf_peek_count(const uint8_t* src, int64_t src_len) {
  if (src_len < 16 || std::memcmp(src, "BFC1", 4) != 0) return -1;
  int64_t n;
  std::memcpy(&n, src + 4, 8);
  return n;
}

// ---------------------------------------------------------------------------
// lzb: greedy LZ77 byte compressor (varint-framed, 64KB window)
// ---------------------------------------------------------------------------
//
// Frame: "LZB1" + varint(raw_len) + sequence of tokens.
// Token: control byte C.
//   C & 0x80 set  -> match: len = (C & 0x7f) + MIN_MATCH, followed by
//                    varint(distance)
//   C & 0x80 zero -> literal run: len = C + 1 literal bytes follow
//                    (runs longer than 128 emit multiple tokens)

static const int LZB_MIN_MATCH = 4;
static const int LZB_HASH_BITS = 16;

static inline uint32_t lzb_hash(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return (v * 2654435761u) >> (32 - LZB_HASH_BITS);
}

static inline uint8_t* put_varint(uint8_t* out, uint64_t v) {
  while (v >= 0x80) {
    *out++ = static_cast<uint8_t>(v) | 0x80;
    v >>= 7;
  }
  *out++ = static_cast<uint8_t>(v);
  return out;
}

static inline const uint8_t* get_varint(const uint8_t* in, const uint8_t* end,
                                        uint64_t* v) {
  uint64_t r = 0;
  int shift = 0;
  while (in < end) {
    uint8_t b = *in++;
    r |= static_cast<uint64_t>(b & 0x7f) << shift;
    if (!(b & 0x80)) { *v = r; return in; }
    shift += 7;
    if (shift > 63) return nullptr;
  }
  return nullptr;
}

int64_t lzb_max_compressed_size(int64_t n) {
  // True worst case is NOT all-literals (n + n/128): alternating
  // [4-byte match][1-byte literal run] emits up to 4 + 2 = 6 bytes per
  // 5 input bytes (control + 3-byte varint distance for the match, then
  // a token byte + the literal) — 1.2x expansion.  Bound with n + n/4
  // (1.25x), which dominates every mix of matches (out <= in) and
  // literal runs (out <= in + runs, runs <= in/5 between matches,
  // <= in/128 otherwise).  Undersizing this corrupted the heap on real
  // 12.8 MB activation payloads (r5).
  return 24 + n + n / 4;
}

int64_t lzb_compress(const uint8_t* src, int64_t n, uint8_t* dst) {
  if (n < 0) return -1;
  uint8_t* out = dst;
  std::memcpy(out, "LZB1", 4); out += 4;
  out = put_varint(out, static_cast<uint64_t>(n));

  int32_t head[1 << LZB_HASH_BITS];
  std::fill(head, head + (1 << LZB_HASH_BITS), -1);

  int64_t i = 0, lit_start = 0;
  auto flush_literals = [&](int64_t upto) {
    int64_t len = upto - lit_start;
    while (len > 0) {
      int64_t take = std::min<int64_t>(len, 128);
      *out++ = static_cast<uint8_t>(take - 1);
      std::memcpy(out, src + lit_start, take);
      out += take;
      lit_start += take;
      len -= take;
    }
  };

  while (i + LZB_MIN_MATCH <= n) {
    uint32_t h = lzb_hash(src + i);
    int64_t cand = head[h];
    head[h] = static_cast<int32_t>(i);
    if (cand >= 0 && i - cand <= 0xffff &&
        std::memcmp(src + cand, src + i, LZB_MIN_MATCH) == 0) {
      int64_t len = LZB_MIN_MATCH;
      int64_t maxlen = std::min<int64_t>(n - i, 127 + LZB_MIN_MATCH);
      while (len < maxlen && src[cand + len] == src[i + len]) ++len;
      flush_literals(i);
      *out++ = static_cast<uint8_t>(0x80 | (len - LZB_MIN_MATCH));
      out = put_varint(out, static_cast<uint64_t>(i - cand));
      i += len;
      lit_start = i;
    } else {
      ++i;
    }
  }
  flush_literals(n);
  return out - dst;
}

int64_t lzb_decompressed_size(const uint8_t* src, int64_t src_len) {
  if (src_len < 5 || std::memcmp(src, "LZB1", 4) != 0) return -1;
  uint64_t n;
  const uint8_t* p = get_varint(src + 4, src + src_len, &n);
  return p ? static_cast<int64_t>(n) : -1;
}

int64_t lzb_decompress(const uint8_t* src, int64_t src_len, uint8_t* dst,
                       int64_t dst_len) {
  if (src_len < 5 || std::memcmp(src, "LZB1", 4) != 0) return -1;
  const uint8_t* end = src + src_len;
  uint64_t n;
  const uint8_t* in = get_varint(src + 4, end, &n);
  if (!in || static_cast<int64_t>(n) > dst_len) return -1;
  uint8_t* out = dst;
  uint8_t* out_end = dst + n;
  while (out < out_end && in < end) {
    uint8_t c = *in++;
    if (c & 0x80) {
      int64_t len = (c & 0x7f) + LZB_MIN_MATCH;
      uint64_t dist;
      in = get_varint(in, end, &dist);
      if (!in || dist == 0 || out - dst < static_cast<int64_t>(dist) ||
          out + len > out_end) return -1;
      const uint8_t* from = out - dist;
      for (int64_t k = 0; k < len; ++k) out[k] = from[k];  // overlap-safe
      out += len;
    } else {
      int64_t len = c + 1;
      if (in + len > end || out + len > out_end) return -1;
      std::memcpy(out, in, len);
      in += len;
      out += len;
    }
  }
  return (out == out_end) ? static_cast<int64_t>(n) : -1;
}

}  // extern "C"
