// Host input-staging ring: the native data plane of the dispatcher.
//
// Role parity: the reference's compute node stages incoming activations in
// a bounded queue between its socket thread and its predict thread
// (reference src/node.py:80-91, Queue(1000) at src/node.py:114); its
// dispatcher feeds the chain from a Python loop one message at a time
// (src/dispatcher.py:90-93).  Both sides pay a Python-object hop per
// sample.  Here the hot path is native: producers memcpy samples into
// preallocated aligned slots (no allocation, no GIL between samples — the
// Python binding releases it around the blocking call), and the consumer
// drains a whole pipeline chunk as ONE contiguous block laid out exactly
// like the SPMD engine's [chunk, microbatch, buf_elems] device buffer, so
// the subsequent jax.device_put is a single straight copy.
//
// Concurrency: one mutex + two condvars (slots-free / items-ready), MPSC
// capable. close() wakes everyone; pops after close drain the remaining
// backlog then report end-of-stream.  All waits are bounded (timeout_ms) so a
// stalled peer can never wedge the host runtime (the failure mode the
// reference's blocking socket loops have, SURVEY.md §5).

#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <vector>

namespace {

struct Ring {
  int64_t slot_bytes;
  int64_t n_slots;
  std::vector<uint8_t> buf;     // n_slots * slot_bytes, single allocation
  std::vector<int64_t> fill;    // bytes actually written per slot
  int64_t head = 0;             // next slot to pop
  int64_t count = 0;            // occupied slots
  bool closed = false;
  std::mutex mu;
  std::condition_variable can_push;
  std::condition_variable can_pop;

  Ring(int64_t sb, int64_t ns)
      : slot_bytes(sb), n_slots(ns),
        buf(static_cast<size_t>(sb * ns)), fill(static_cast<size_t>(ns), 0) {}

  uint8_t* slot(int64_t idx) {
    return buf.data() + (idx % n_slots) * slot_bytes;
  }
};

}  // namespace

extern "C" {

// Create a ring of n_slots slots of slot_bytes each.  Returns an opaque
// handle (never null for sane sizes; null on overflow-ish inputs).
void* staging_create(int64_t slot_bytes, int64_t n_slots) {
  if (slot_bytes <= 0 || n_slots <= 0 ||
      slot_bytes > (int64_t(1) << 40) / n_slots) {
    return nullptr;
  }
  return new Ring(slot_bytes, n_slots);
}

void staging_destroy(void* h) { delete static_cast<Ring*>(h); }

// Copy one sample (n <= slot_bytes) into the next free slot; short samples
// are zero-padded to slot_bytes (the homogeneous-buffer padding the SPMD
// engine otherwise does in Python).  Blocks while the ring is full.
// Returns 1 on success, 0 on timeout, -1 if closed or n > slot_bytes.
int staging_push(void* h, const uint8_t* src, int64_t n, int64_t timeout_ms) {
  Ring* r = static_cast<Ring*>(h);
  if (n < 0 || n > r->slot_bytes) return -1;
  std::unique_lock<std::mutex> lk(r->mu);
  if (!r->can_push.wait_for(lk, std::chrono::milliseconds(timeout_ms), [&] {
        return r->count < r->n_slots || r->closed;
      })) {
    return 0;
  }
  if (r->closed) return -1;
  int64_t idx = r->head + r->count;
  uint8_t* dst = r->slot(idx);
  std::memcpy(dst, src, static_cast<size_t>(n));
  if (n < r->slot_bytes) {
    std::memset(dst + n, 0, static_cast<size_t>(r->slot_bytes - n));
  }
  r->fill[idx % r->n_slots] = n;
  r->count++;
  lk.unlock();
  r->can_pop.notify_one();
  return 1;
}

// Drain up to `want` slots into `dst` (want * slot_bytes bytes), zero-
// filling unpopped tail slots — dst comes back laid out as a full
// [want, slot_bytes] chunk block regardless of how many samples were
// ready.  Blocks until at least one sample (or close/timeout).
// Returns: number of samples popped (>=1), 0 on timeout, -1 on
// end-of-stream (closed and drained).
int64_t staging_pop_block(void* h, uint8_t* dst, int64_t want,
                          int64_t timeout_ms) {
  Ring* r = static_cast<Ring*>(h);
  std::unique_lock<std::mutex> lk(r->mu);
  if (!r->can_pop.wait_for(lk, std::chrono::milliseconds(timeout_ms), [&] {
        return r->count > 0 || r->closed;
      })) {
    return 0;
  }
  if (r->count == 0) return -1;  // closed and drained
  int64_t got = r->count < want ? r->count : want;
  for (int64_t i = 0; i < got; ++i) {
    std::memcpy(dst + i * r->slot_bytes, r->slot(r->head + i),
                static_cast<size_t>(r->slot_bytes));
  }
  r->head = (r->head + got) % r->n_slots;
  r->count -= got;
  lk.unlock();
  if (got > 0) r->can_push.notify_all();
  if (want > got) {
    std::memset(dst + got * r->slot_bytes, 0,
                static_cast<size_t>((want - got) * r->slot_bytes));
  }
  return got;
}

// End-of-stream: producers stop, consumers drain then see -1.
void staging_close(void* h) {
  Ring* r = static_cast<Ring*>(h);
  {
    std::lock_guard<std::mutex> lk(r->mu);
    r->closed = true;
  }
  r->can_push.notify_all();
  r->can_pop.notify_all();
}

// Occupancy snapshot (for metrics/backpressure decisions).
int64_t staging_depth(void* h) {
  Ring* r = static_cast<Ring*>(h);
  std::lock_guard<std::mutex> lk(r->mu);
  return r->count;
}

}  // extern "C"
