"""Command-line interface: ``python -m defer_tpu <command>``.

The reference deploys by running standalone scripts on each machine
(``python node.py`` per compute node + a driver for the dispatcher,
reference src/node.py:126-127, test/test.py); the SPMD design needs no
per-node processes, so the CLI's job is inspection and benchmarking of a
deployment from one controller:

  models     list the model zoo
  partition  show the stage table for a model + cut spec (DOT optional)
  bench      timed-window pipeline throughput vs single-device baseline
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def _get_model(name: str):
    from . import models
    if not hasattr(models, name):
        raise SystemExit(
            f"unknown model {name!r}; try: python -m defer_tpu models")
    return getattr(models, name)()


def cmd_models(_args):
    from . import models
    for n in models.__all__:
        obj = getattr(models, n)
        if callable(obj):
            print(n)
        else:
            print(f"{n}  (cut list, {len(obj)} cuts)")


def cmd_partition(args):
    import jax

    from . import partition, valid_cut_points
    from .graph.viz import summary, to_dot

    graph = _get_model(args.model)
    cuts = args.cuts.split(",") if args.cuts else None
    stages = partition(graph, cuts, num_stages=args.stages)
    print(f"{graph.name}: {len(graph.nodes)} nodes, "
          f"{len(valid_cut_points(graph))} valid cut points")
    for s in stages:
        print(f"  {s}")
    if args.summary:
        print(summary(graph))
    if args.dot:
        stage_of = {name: s.index for s in stages for name in s.node_names}
        with open(args.dot, "w") as f:
            f.write(to_dot(graph, stage_of=stage_of))
        print(f"wrote {args.dot}")
    del jax  # imported for backend side effects only


def cmd_bench(args):
    import jax
    import jax.numpy as jnp

    from . import SpmdPipeline, partition, pipeline_mesh

    graph = _get_model(args.model)
    params = graph.init(jax.random.key(0))
    cuts = args.cuts.split(",") if args.cuts else None
    stages = partition(graph, cuts, num_stages=args.stages)
    n = len(stages)
    pipe = SpmdPipeline(
        stages, params, mesh=pipeline_mesh(n), microbatch=args.microbatch,
        chunk=args.chunk, wire=args.wire,
        buffer_dtype=jnp.bfloat16
        if jax.default_backend() == "tpu" else jnp.float32)
    in_spec = stages[0].in_spec
    xs = pipe.stage_inputs(np.zeros(
        (args.chunk, args.microbatch) + in_spec.shape, np.float32))

    def step():
        pipe.push(xs, n_real=args.chunk)
        jax.block_until_ready(pipe._a)

    step()  # compile
    t0 = time.perf_counter()
    iters = 0
    while time.perf_counter() - t0 < args.seconds:
        step()
        iters += 1
    dt = time.perf_counter() - t0
    ips = iters * args.chunk * args.microbatch / dt
    print(json.dumps({
        "metric": f"{args.model}_{n}stage_throughput",
        "value": round(ips, 3), "unit": "inferences/sec",
        "wire": args.wire,
        "devices": len(jax.devices()),
        **pipe.metrics.as_dict()}))


def main(argv=None):
    ap = argparse.ArgumentParser(prog="python -m defer_tpu")
    sub = ap.add_subparsers(dest="cmd", required=True)

    sub.add_parser("models", help="list the model zoo")

    p = sub.add_parser("partition", help="show the stage table")
    p.add_argument("--model", required=True)
    p.add_argument("--stages", type=int)
    p.add_argument("--cuts")
    p.add_argument("--dot", help="write a DOT graph with stage coloring")
    p.add_argument("--summary", action="store_true")

    b = sub.add_parser("bench", help="timed pipeline throughput")
    b.add_argument("--model", default="resnet_tiny")
    b.add_argument("--stages", type=int)
    b.add_argument("--cuts")
    b.add_argument("--chunk", type=int, default=16)
    b.add_argument("--microbatch", type=int, default=1)
    b.add_argument("--wire", default="buffer", choices=["buffer", "int8"])
    b.add_argument("--seconds", type=float, default=5.0)

    args = ap.parse_args(argv)
    {"models": cmd_models, "partition": cmd_partition,
     "bench": cmd_bench}[args.cmd](args)


if __name__ == "__main__":
    main()
