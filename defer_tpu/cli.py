"""Command-line interface: ``python -m defer_tpu <command>``.

The reference deploys by running standalone scripts on each machine
(``python node.py`` per compute node + a driver for the dispatcher,
reference src/node.py:126-127, test/test.py); the SPMD design needs no
per-node processes, so the CLI's job is inspection and benchmarking of a
deployment from one controller:

  models     list the model zoo
  partition  show the stage table for a model + cut spec (DOT optional)
  plan       comm-aware bottleneck partition plan (exact solver, per-hop
             codec selection, quantile comparison — docs/PLANNER.md)
  bench      timed-window pipeline throughput vs single-device baseline
  export     write per-stage StableHLO artifacts for a partition
  node       run one standalone stage node (recv -> stage -> relay), the
             working equivalent of the reference's ``python node.py``
  chain      export + spawn N local node processes + stream + verify
  monitor    live top-style view of a running chain: subscribe to every
             node's obs_push telemetry, aggregate per stage/replica,
             highlight the bottleneck, flag stragglers
             (docs/OBSERVABILITY.md)
  serve      multi-tenant serving front door over one deployed chain:
             weighted-fair admission, continuous batching, SLO-aware
             shedding (docs/SERVING.md)
  serve-client  open-loop load generator (seeded Poisson + bursts)
             against a serve front door
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def _get_model(name: str):
    from . import models
    if not hasattr(models, name):
        raise SystemExit(
            f"unknown model {name!r}; try: python -m defer_tpu models")
    return getattr(models, name)()


# -- telemetry plumbing (docs/OBSERVABILITY.md) ----------------------------

def _add_obs_flags(p):
    p.add_argument("--trace-out", default=None, metavar="FILE",
                   help="write spans as Chrome trace-event JSON "
                        "(open at https://ui.perfetto.dev)")
    p.add_argument("--metrics-out", default=None, metavar="FILE",
                   help="write a JSON snapshot of the metrics registry "
                        "(counters, byte counts, latency percentiles)")


def _obs_begin(args, *, process: str = "dispatcher"):
    """Enable the process tracer when a trace export was requested."""
    if getattr(args, "trace_out", None):
        from .obs import enable_tracing
        enable_tracing(process=process).start_trace()


def _obs_finish(args, extra: dict | None = None):
    """Write the requested telemetry artifacts (no-op without flags)."""
    if getattr(args, "trace_out", None):
        from .obs import export_chrome_trace
        export_chrome_trace(args.trace_out)
        print(f"trace -> {args.trace_out}", file=sys.stderr)
    if getattr(args, "metrics_out", None):
        from .obs import REGISTRY
        snap = {"registry": REGISTRY.snapshot()}
        if extra:
            snap.update(extra)
        with open(args.metrics_out, "w") as f:
            json.dump(snap, f, indent=2, default=str)
            f.write("\n")
        print(f"metrics -> {args.metrics_out}", file=sys.stderr)


def _add_overlap_flags(p):
    """Transport-overlap tuning shared by ``node`` and ``chain``."""
    p.add_argument("--no-overlap", action="store_true",
                   help="serial recv->infer->send node loop (the pre-"
                        "overlap baseline scripts/chain_overlap_smoke.py "
                        "measures against)")
    p.add_argument("--rx-depth", type=int, default=8, metavar="N",
                   help="decoded frames buffered by each rx channel")
    p.add_argument("--tx-depth", type=int, default=8, metavar="N",
                   help="frames queued to each tx channel before the "
                        "producer blocks")
    p.add_argument("--inflight", type=int, default=2, metavar="N",
                   help="stage dispatches kept un-synced per node (JAX "
                        "async dispatch window)")
    p.add_argument("--sock-buf", type=int, default=0, metavar="BYTES",
                   help="SO_SNDBUF/SO_RCVBUF for every data socket "
                        "(0 = kernel default for `node`; `chain` sizes "
                        "it to the partition's fattest boundary frame)")


def _add_cost_flags(p):
    """Planner cost-model knobs shared by ``plan`` and ``partition``."""
    p.add_argument("--codecs", default="", metavar="LIST",
                   help="comma list of candidate hop codecs "
                        "(default: raw,lzb,bf8,bf16)")
    p.add_argument("--link-bw", type=float, default=0.0, metavar="BYTES_S",
                   help="hop link bandwidth in bytes/s (default: the "
                        "detected chip generation's one-way ICI figure; "
                        "set explicitly for DCN/ethernet hops)")
    p.add_argument("--calibrate", action="store_true",
                   help="micro-bench the codec table on this host "
                        "instead of using analytic defaults")
    p.add_argument("--ici-bw", type=float, default=0.0, metavar="BYTES_S",
                   help="device-to-device interconnect bandwidth for "
                        "ici-tier hops (default: the chip generation's "
                        "one-way ICI figure, like --link-bw)")
    p.add_argument("--hop-tier-map", default="", metavar="CUT=TIER,...",
                   help="declare colocated boundaries to the cost model "
                        "(cut node name = ici|local|shm|device): those "
                        "hops are scored on the tier pseudo-codec "
                        "instead of the cheapest wire codec, so cut "
                        "placement exploits same-mesh colocation "
                        "(docs/PLANNER.md)")
    p.add_argument("--calibrated", default="", metavar="FILE",
                   help="overlay a CalibratedConstants JSON artifact "
                        "(chain --emit-calibration / "
                        "plan.calibrate.fit_from_stats) on the cost "
                        "model: measured codec throughputs and "
                        "host-sync/ici/local/wire bandwidths replace "
                        "the analytic defaults (docs/PLANNER.md)")


def _parse_hop_tier_map(spec: str) -> dict | None:
    out = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        cut, sep, tier = part.rpartition("=")
        if not sep or tier not in ("ici", "local", "shm", "device",
                                   "tcp"):
            raise SystemExit(f"--hop-tier-map: {part!r} is not "
                             f"CUT=ici|local|shm|device|tcp")
        out[cut] = tier
    return out or None


def _cost_model(args, graph, *, node_costs=None):
    """Build the ``plan.StageCostModel`` the CLI flags describe."""
    from .plan import DEFAULT_CODECS, StageCostModel, calibrate_codecs
    names = [c for c in (args.codecs.split(",") if args.codecs
                         else list(DEFAULT_CODECS)) if c]
    if args.calibrate or any(n not in DEFAULT_CODECS for n in names):
        # unknown names (bf12, ...) have no analytic row: measure them
        codecs = calibrate_codecs(tuple(names))
    else:
        codecs = {n: DEFAULT_CODECS[n] for n in names}
    cost = StageCostModel(graph, batch=getattr(args, "batch", 1),
                          link_bw_s=args.link_bw or None,
                          ici_bw_s=getattr(args, "ici_bw", 0.0) or None,
                          codecs=codecs, node_costs=node_costs,
                          hop_tiers=_parse_hop_tier_map(
                              getattr(args, "hop_tier_map", "")))
    calibrated = getattr(args, "calibrated", "")
    if calibrated:
        from .plan import CalibratedConstants
        cost = CalibratedConstants.load(calibrated).apply(cost)
    return cost


def _partition_json(graph, stages, plan=None) -> dict:
    """Machine-readable partition description (``--json``) — what
    ``scripts/plan_smoke.py`` / ``benchmarks/run.py`` parse instead of
    scraping the human stage table."""
    from .graph.analysis import max_activation_bytes, valid_cut_points
    from .partition.stage import buffer_footprint
    cuts = [s.output_name for s in stages[:-1]]
    doc = {
        "model": graph.name,
        "num_stages": len(stages),
        "cuts": cuts,
        "valid_cut_points": valid_cut_points(graph),
        "max_activation_bytes": max_activation_bytes(graph, cuts),
        "stages": [{
            "index": s.index,
            "nodes": len(s.node_names),
            "input": s.input_name,
            "output": s.output_name,
            "in_shape": list(s.in_spec.shape),
            "out_shape": list(s.out_spec.shape),
            "boundary_bytes": s.out_spec.size * s.out_spec.dtype.itemsize,
        } for s in stages],
        "buffer": buffer_footprint(stages),
    }
    if plan is not None:
        doc["plan"] = plan.to_json()
    return doc


def cmd_models(_args):
    from . import models
    for n in models.__all__:
        obj = getattr(models, n)
        if callable(obj):
            print(n)
        else:
            print(f"{n}  (cut list, {len(obj)} cuts)")


def cmd_partition(args):
    import jax

    from . import partition, valid_cut_points
    from .graph.viz import summary, to_dot

    graph = _get_model(args.model)
    cuts = args.cuts.split(",") if args.cuts else None
    if cuts is not None and args.balance != "flops":
        raise SystemExit(f"--cuts and --balance {args.balance} conflict: "
                         "explicit cuts leave nothing to balance")
    if cuts is None and args.balance != "flops" and args.stages is None:
        raise SystemExit(f"--balance {args.balance} requires --stages")
    if cuts is None and args.stages is not None:
        # branching graphs lock most nodes inside their merge regions:
        # name the offending merge nodes (and point at plan --dag)
        # instead of dying deep in the cut search
        from .graph.analysis import linear_cut_shortage
        shortage = linear_cut_shortage(graph, args.stages)
        if shortage:
            raise SystemExit(f"partition: {shortage}")
    plan = None
    if cuts is None and args.balance == "measured":
        # latency-balanced auto-cuts: time every op on THIS backend and
        # snap quantiles of measured (not analytic) cost to valid cuts
        from .graph.analysis import auto_cut_points
        from .utils.profiling import measured_node_costs
        params = graph.init(jax.random.key(0))
        costs = measured_node_costs(graph, params, batch=args.batch)
        cuts = auto_cut_points(graph, args.stages, costs=costs)
        if not args.json:
            print(f"measured-balanced cuts: {cuts}")
    elif cuts is None and args.balance == "bottleneck":
        # comm-aware exact solver: minimize max(compute, comm) per stage
        from .plan import solve
        plan = solve(graph, args.stages, _cost_model(args, graph))
        cuts = plan.cuts
        if not args.json:
            print(f"bottleneck cuts: {cuts} "
                  f"(hop codecs {plan.codecs}, predicted bottleneck "
                  f"{plan.bottleneck_s * 1e3:.4f} ms, {plan.bound_by}-"
                  f"bound)")
    stages = partition(graph, cuts, num_stages=args.stages
                       if cuts is None else None)
    if args.json:
        print(json.dumps(_partition_json(graph, stages, plan)))
        if args.dot:
            stage_of = {name: s.index for s in stages
                        for name in s.node_names}
            with open(args.dot, "w") as f:
                f.write(to_dot(graph, stage_of=stage_of))
        del jax
        return
    print(f"{graph.name}: {len(graph.nodes)} nodes, "
          f"{len(valid_cut_points(graph))} valid cut points")
    for s in stages:
        print(f"  {s}")
    # padded-buffer waste: every hop of the homogeneous SPMD transfer
    # buffer pays buf_elems regardless of what the boundary carries
    from .partition.stage import buffer_footprint
    fp = buffer_footprint(stages)
    print(f"  transfer buffer: {fp['buf_elems']} elems/hop "
          f"(max stage boundary; every hop pays this)")
    for s, util in zip(stages, fp["hop_utilization"]):
        dst = f"stage {s.index + 1}" if s.index + 1 < len(stages) \
            else "dispatcher (wrap)"
        print(f"    hop {s.index}->{dst}: carries {s.out_spec.size} elems "
              f"({util:.1%} of buffer)")
    if args.summary:
        print(summary(graph))
    if args.dot:
        stage_of = {name: s.index for s in stages for name in s.node_names}
        with open(args.dot, "w") as f:
            f.write(to_dot(graph, stage_of=stage_of))
        print(f"wrote {args.dot}")
    del jax  # imported for backend side effects only


def _linear_critical_path_s(plan) -> float:
    """Per-sample latency of a chain plan: the sum of per-stage
    ``max(compute, comm)`` — a chain's stage graph IS one path."""
    comm = plan.hop_comm_s + [0.0]
    return sum(max(c, h) for c, h in zip(plan.stage_compute_s, comm))


def _cmd_plan_dag(args, graph, cm, doc: dict, *,
                  hop_tiers: dict | None) -> None:
    """``plan --dag``: branch-parallel stage graph vs the best linear
    chain at the same process budget (docs/PLANNER.md)."""
    from .plan.dag import best_linear_plan, solve_dag
    num_nodes = args.nodes or args.stages
    if not num_nodes:
        raise SystemExit("plan --dag requires --nodes N (process "
                         "budget; --stages N also works)")
    dag = solve_dag(graph, cm, num_nodes=num_nodes, hop_tiers=hop_tiers)
    linear = best_linear_plan(graph, cm, num_nodes)
    lin_cp = _linear_critical_path_s(linear)
    doc["plan"] = dag.to_json()
    doc["linear"] = linear.to_json()
    doc["linear"]["critical_path_ms"] = round(lin_cp * 1e3, 6)
    doc["predicted_speedup_vs_linear"] = round(
        linear.bottleneck_s / dag.bottleneck_s, 4) \
        if dag.bottleneck_s > 0 else None
    doc["predicted_latency_speedup_vs_linear"] = round(
        lin_cp / dag.critical_path_s, 4) \
        if dag.critical_path_s > 0 else None
    if args.json:
        print(json.dumps(doc))
        return
    print(f"{graph.name}: DAG plan, {dag.num_stages} stage vertices / "
          f"{num_nodes} node budget, cost model "
          f"{cm.describe()['node_costs']}")
    for v in dag.vertices:
        mark = " <- bottleneck" if v.vid == dag.bottleneck_vertex else ""
        role = ""
        if v.fan == "broadcast":
            role = f" fork x{len(v.next)}"
        if v.join >= 2:
            role += f" join x{v.join}"
        print(f"  {v.label:>11}: compute {v.compute_s * 1e3:10.4f} ms | "
              f"hop {v.comm_s * 1e3:10.4f} ms ({v.codec})"
              f"{role}{mark}")
    print(f"  parallel regions: "
          + (", ".join(f"{r['fork']}->{r['join']} x{r['paths']}"
                       for r in dag.parallel_regions) or "none "
             "(linear chain is optimal at this budget)"))
    print(f"  predicted bottleneck {dag.bottleneck_s * 1e3:.4f} ms, "
          f"critical path {dag.critical_path_s * 1e3:.4f} ms")
    print(f"  linear baseline ({linear.num_stages} stages): bottleneck "
          f"{linear.bottleneck_s * 1e3:.4f} ms, critical path "
          f"{lin_cp * 1e3:.4f} ms (speedup "
          f"{doc['predicted_speedup_vs_linear']}x throughput, "
          f"{doc['predicted_latency_speedup_vs_linear']}x latency)")


def cmd_plan(args):
    """Comm-aware bottleneck plan: solve, score the quantile baseline on
    the same cost model, optionally sweep stage counts / replan from a
    telemetry snapshot (docs/PLANNER.md)."""
    from .graph.analysis import auto_cut_points, linear_cut_shortage
    from .plan import evaluate_cuts, solve, sweep_stages

    graph = _get_model(args.model)
    node_costs = None
    if args.measured:
        import jax

        from .utils.profiling import measured_node_costs
        params = graph.init(jax.random.key(0))
        node_costs = measured_node_costs(graph, params, batch=args.batch)
    dag_tiers = None
    if args.dag:
        # the DAG planner validates hop-tier keys against the stage-
        # GRAPH cut namespace (branch-internal hops included) — keep
        # them away from the cost-model constructor's linear check
        dag_tiers = _parse_hop_tier_map(getattr(args, "hop_tier_map", ""))
        args.hop_tier_map = ""
    cm = _cost_model(args, graph, node_costs=node_costs)
    doc: dict = {"model": graph.name, "cost_model": cm.describe()}
    if args.dag:
        _cmd_plan_dag(args, graph, cm, doc, hop_tiers=dag_tiers)
        return
    if args.stages is not None and not args.nodes and not args.sweep:
        # pre-validate BEFORE the DP: an oversubscribed stage count on a
        # branching graph must name the merge nodes locking the cuts
        # (and point at --dag), not die deep in the solver
        shortage = linear_cut_shortage(graph, args.stages)
        if shortage:
            raise SystemExit(f"plan: {shortage}")
    if args.nodes:
        # hybrid pipeline/data-parallel: joint cuts + replica counts for
        # a process budget, vs the best cuts-only plan it must beat
        from .plan import solve_replicated
        plan = solve_replicated(graph, cm, num_nodes=args.nodes)
        doc["plan"] = plan.to_json()
        from .graph.analysis import valid_cut_points
        max_s = min(args.nodes, len(valid_cut_points(graph)) + 1)
        cuts_only = min((solve(graph, s, cm) for s in range(1, max_s + 1)),
                        key=lambda p: p.bottleneck_s)
        doc["cuts_only"] = cuts_only.to_json()
        doc["predicted_speedup_vs_cuts_only"] = round(
            cuts_only.bottleneck_s / plan.bottleneck_s, 4) \
            if plan.bottleneck_s > 0 else None
    elif args.sweep:
        sw = sweep_stages(graph, cm, max_stages=args.sweep,
                          latency_target_s=args.target_ms / 1e3
                          if args.target_ms else None)
        doc["sweep"] = [p.to_json() for p in sw["plans"]]
        doc["target_met"] = sw["target_met"]
        plan = sw["recommended"]
        doc["recommended"] = plan.to_json()
    else:
        if args.stages is None:
            raise SystemExit(
                "plan requires --stages (or --sweep MAX / --nodes N)")
        plan = solve(graph, args.stages, cm)
        doc["plan"] = plan.to_json()
    if plan.num_stages > 1:
        # the measurable baseline: greedy quantile cuts scored on the
        # SAME cost model the solver optimized
        qcuts = auto_cut_points(graph, plan.num_stages, costs=node_costs)
        qplan = evaluate_cuts(graph, qcuts, cm, objective="quantile")
        doc["quantile"] = qplan.to_json()
        doc["predicted_speedup_vs_quantile"] = round(
            qplan.bottleneck_s / plan.bottleneck_s, 4) \
            if plan.bottleneck_s > 0 else None
    if args.replan:
        from .plan import replan as _do_replan
        with open(args.replan) as f:
            snap = json.load(f)
        rp = _do_replan(graph, plan, snap.get("registry", snap), cm)
        doc["replan"] = rp.to_json()
    if args.json:
        print(json.dumps(doc))
        return
    print(f"{graph.name}: {plan.num_stages} stages, objective "
          f"{plan.objective}, cost model {cm.describe()['node_costs']} "
          f"(gen {cm.gen}, link {cm.link_bw_s:.3g} B/s)")
    comm = plan.hop_comm_s + [0.0]
    codecs = plan.codecs + ["-"]
    reps = getattr(plan, "replicas", None)
    for k, comp in enumerate(plan.stage_compute_s):
        mark = " <- bottleneck" if k == plan.bottleneck_stage else ""
        rep = ""
        if reps is not None and reps[k] > 1:
            rep = (f" x{reps[k]} replicas -> "
                   f"{comp / reps[k] * 1e3:.4f} ms")
        print(f"  stage {k}: compute {comp * 1e3:10.4f} ms{rep} | "
              f"hop {comm[k] * 1e3:10.4f} ms ({codecs[k]}){mark}")
    print(f"  predicted bottleneck {plan.bottleneck_s * 1e3:.4f} ms "
          f"({plan.bound_by}-bound) -> "
          f"{plan.predicted_throughput_per_s(cm.batch):.2f} inf/s")
    print(f"  cuts: {','.join(plan.cuts) or '-'}")
    if "cuts_only" in doc:
        co = doc["cuts_only"]
        print(f"  cuts-only baseline ({co['num_stages']} stages): "
              f"bottleneck {co['bottleneck_ms']:.4f} ms (speedup "
              f"{doc['predicted_speedup_vs_cuts_only']}x with "
              f"{doc['plan']['num_nodes']} nodes)")
    if "quantile" in doc:
        q = doc["quantile"]
        print(f"  quantile baseline: bottleneck {q['bottleneck_ms']:.4f} "
              f"ms at cuts {','.join(q['cuts'])} "
              f"(speedup {doc['predicted_speedup_vs_quantile']}x)")
    if "replan" in doc:
        r = doc["replan"]
        print(f"  replan: moved={r['moved']} corrections="
              f"{r['corrections']} predicted improvement "
              f"{r['predicted_improvement']}x")
    if args.sweep:
        met = doc["target_met"]
        print(f"  sweep: recommended {plan.num_stages} stages"
              + (f" (target {'met' if met else 'NOT met'})"
                 if met is not None else ""))


def cmd_bench(args):
    import jax
    import jax.numpy as jnp

    from . import SpmdPipeline, partition, pipeline_mesh

    _obs_begin(args)
    graph = _get_model(args.model)
    params = graph.init(jax.random.key(0))
    cuts = args.cuts.split(",") if args.cuts else None
    if cuts is not None and args.balance != "flops":
        raise SystemExit(f"--cuts and --balance {args.balance} conflict: "
                         "explicit cuts leave nothing to balance")
    if cuts is None and args.stages is None:
        # default deployment: one stage per device
        args.stages = len(jax.devices())
    stages = partition(graph, cuts, num_stages=args.stages,
                       objective="bottleneck"
                       if cuts is None and args.balance == "bottleneck"
                       else "quantile")
    n = len(stages)
    pipe = SpmdPipeline(
        stages, params, mesh=pipeline_mesh(n), microbatch=args.microbatch,
        chunk=args.chunk, wire=args.wire,
        buffer_dtype=jnp.bfloat16
        if jax.default_backend() == "tpu" else jnp.float32)
    in_spec = stages[0].in_spec
    xs = pipe.stage_inputs(np.zeros(
        (args.chunk, args.microbatch) + in_spec.shape, np.float32))

    def step():
        pipe.push(xs, n_real=args.chunk)
        jax.block_until_ready(pipe._a)

    from .obs import tracer as _tracer

    step()  # compile
    # the compile push must not pollute the exported steady-state
    # percentiles (it is seconds; the window pushes are milliseconds)
    pipe.metrics.clear_counters()
    with _tracer().span("dispatcher.bench_window",
                        {"model": args.model, "chunk": args.chunk,
                         "microbatch": args.microbatch}):
        t0 = time.perf_counter()
        iters = 0
        while time.perf_counter() - t0 < args.seconds:
            step()
            iters += 1
        dt = time.perf_counter() - t0
    ips = iters * args.chunk * args.microbatch / dt
    if args.trace_out or args.metrics_out:
        # per-stage spans + latency histograms for the exports (times the
        # deployed branches; not part of the throughput window above)
        pipe.stage_latencies(iters=3)
    print(json.dumps({
        "metric": f"{args.model}_{n}stage_throughput",
        "value": round(ips, 3), "unit": "inferences/sec",
        "wire": args.wire,
        "devices": len(jax.devices()),
        **pipe.metrics.as_dict()}))
    _obs_finish(args, {"pipeline": pipe.metrics.as_dict()})


def cmd_export(args):
    import jax

    from . import partition
    from .utils.export import export_pipeline

    graph = _get_model(args.model)
    params = graph.init(jax.random.key(0))
    cuts = args.cuts.split(",") if args.cuts else None
    stages = partition(graph, cuts, num_stages=args.stages)
    paths = export_pipeline(stages, params, args.out, batch=args.batch)
    for p in paths:
        print(p)


def _apply_sock_buf(args, *, auto_bytes: int | None = None):
    """``--sock-buf N`` sizes SO_SNDBUF/SO_RCVBUF on every data socket of
    this process — and, via the environment, of any chain children.

    ``auto_bytes`` (the partition's fattest boundary frame, from
    ``graph.analysis.max_activation_bytes``) sizes the default when no
    explicit ``--sock-buf`` was given: kernel buffers scale with what
    the chain actually ships instead of a flat constant."""
    buf = getattr(args, "sock_buf", 0)
    if not buf and auto_bytes:
        from .transport.framed import default_sock_buf
        buf = default_sock_buf(auto_bytes)
        print(f"sock-buf: auto {buf} bytes "
              f"(2x max boundary frame {auto_bytes})", file=sys.stderr)
    if buf:
        import os

        from .transport import framed
        framed.SOCK_SNDBUF = framed.SOCK_RCVBUF = buf
        os.environ["DEFER_SOCK_SNDBUF"] = str(buf)
        os.environ["DEFER_SOCK_RCVBUF"] = str(buf)


def _start_prom(args, who: str):
    """``--prom-port N``: serve the process registry's Prometheus
    exposition over stdlib HTTP (0 = ephemeral port, printed)."""
    if getattr(args, "prom_port", None) is None:
        return
    from .obs.report import start_prom_server
    srv = start_prom_server(args.prom_port)
    print(f"{who}: prometheus exposition on "
          f"http://127.0.0.1:{srv.server_address[1]}/metrics",
          file=sys.stderr, flush=True)


def _parse_co_stage(spec: str) -> dict:
    """``listen=ADDR[;artifact=P][;next=A][;codec=C][;tier=T]
    [;accept=0|1]`` -> dict.  The co-stage grammar uses ``;`` separators
    because ``next`` values may themselves be comma lists (fan-out).
    ``accept`` controls whether this housemate GRANTS inbound tier
    offers (default: its own ``tier`` is not tcp) — independent of the
    outbound policy because a stage whose next hop leaves the process
    may still be the local-tier target of its upstream housemate."""
    kv = {}
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        k, sep, v = part.partition("=")
        if not sep:
            raise SystemExit(f"--co-stage: {part!r} is not key=value")
        kv[k.strip()] = v.strip()
    if "listen" not in kv:
        raise SystemExit(f"--co-stage {spec!r} needs listen=host:port")
    bad = set(kv) - {"listen", "artifact", "next", "codec", "tier",
                     "accept", "device"}
    if bad:
        raise SystemExit(f"--co-stage: unknown keys {sorted(bad)}")
    if kv.get("accept") not in (None, "0", "1"):
        raise SystemExit(f"--co-stage: accept must be 0|1, "
                         f"got {kv['accept']!r}")
    if "device" in kv:
        try:
            kv["device"] = int(kv["device"])
        except ValueError:
            raise SystemExit(f"--co-stage: device must be an integer "
                             f"jax device index, got {kv['device']!r}")
    return kv


def cmd_node(args):
    import threading
    import traceback

    from .runtime.node import StageNode
    from .transport.framed import _codec

    _apply_sock_buf(args)
    _start_prom(args, "node")
    _codec(args.codec)  # loud at boot, not when the first tensor relays

    def boot(artifact, listen, nxt, codec, tier, accept, primary,
             device=None):
        # --fan-in/--replica (and the branch-graph roles --fan/--branch/
        # --join) describe the PRIMARY node's place in a fan topology;
        # housemates always sit on plain local hops (the fan machinery
        # is wire-framed, and colocation next to replication is
        # rejected upstream), so they never inherit any of them
        node = StageNode(artifact, listen, nxt,
                         codec=codec, overlap=not args.no_overlap,
                         rx_depth=args.rx_depth, tx_depth=args.tx_depth,
                         inflight=args.inflight,
                         fan_in=args.fan_in if primary else 1,
                         replica=args.replica if primary else None,
                         fan_mode=args.fan if primary else "rr",
                         branch=args.branch if primary else None,
                         join_in=args.join if primary else 0,
                         infer_delay_s=args.infer_delay_ms / 1e3
                         if primary else 0.0,
                         tier=tier, tier_accept=accept, device=device,
                         failover=args.failover, persist=args.persist)
        what = (f"stage {node.manifest['index']} "
                f"({node.manifest['name']})"
                if node.manifest else "EMPTY (awaiting in-band deploy)")
        if node.replica is not None:
            what += f" replica {node.replica}"
        if node.branch is not None:
            what += f" branch {node.branch}"
        if node.join_in >= 2:
            what += f" join {node.join_in}"
        if node.fan_in > 1:
            what += f" fan-in {node.fan_in}"
        print(f"node: {what} listening on "
              f"{node.address[0]}:{node.address[1]}, next {nxt}"
              f"{' [serial]' if args.no_overlap else ''}",
              file=sys.stderr, flush=True)
        return node

    # colocated stages: every --co-stage boards this process as its own
    # serve thread — the hops between housemates negotiate the local
    # (zero-serialization in-memory) transport tier (docs/TRANSPORT.md)
    accept = (args.tier != "tcp") if args.tier_accept == "auto" \
        else args.tier_accept == "1"
    node = boot(args.artifact, args.listen, args.next, args.codec,
                args.tier, accept, True, args.device)
    co = [boot(kv.get("artifact"), kv["listen"], kv.get("next"),
               kv.get("codec", "raw"), kv.get("tier", args.tier),
               kv["accept"] == "1" if "accept" in kv
               else kv.get("tier", args.tier) != "tcp", False,
               kv.get("device"))
          for kv in map(_parse_co_stage, args.co_stage or [])]
    if args.journal_dir:
        # black-box flight recorder (docs/OBSERVABILITY.md): spill this
        # process's events + obs rows + spans to a crash-safe journal a
        # postmortem can read after a kill -9
        from .obs import recorder, start_journal
        m = node.manifest
        label = (f"stage{m['index']}" if m is not None
                 else f"node{node.address[1]}")
        if args.replica is not None:
            label += f".r{args.replica}"

        def _journal_row(_node=node):
            payload, _, _ = _node.obs_snapshot(
                include_spans=False, subscriber=-101,
                event_cursor=recorder().cursor())
            # events/spans ride their own journal records; the snapshot
            # is the last-known ClusterView-style row
            payload.pop("trace", None)
            payload.pop("events", None)
            return payload

        start_journal(args.journal_dir, label, snapshot_fn=_journal_row)
    counts: dict[int, int] = {}

    def serve_co(i: int):
        try:
            counts[i] = co[i].serve(
                connect_timeout_s=args.connect_timeout)
        except BaseException:  # noqa: BLE001 — a dead co-stage must
            # kill the whole process so the parent sees one attributed
            # failure instead of a wedged chain
            import os
            traceback.print_exc()
            sys.stderr.flush()
            os._exit(1)

    threads = [threading.Thread(target=serve_co, args=(i,), daemon=True)
               for i in range(len(co))]
    for t in threads:
        t.start()
    n = node.serve(connect_timeout_s=args.connect_timeout)
    # the process exits only when EVERY housemate's stream has drained:
    # the primary finishing first must not kill a co-stage mid-relay (a
    # plain node blocks in serve() just the same; a wedged chain is the
    # dispatcher's to kill)
    for t in threads:
        t.join()
    n += sum(counts.values())
    if args.journal_dir:
        from .obs import stop_journal
        stop_journal()   # final spill: the clean-exit journal is whole
    print(f"node: served {n} tensors; chain drained", file=sys.stderr)


def _parse_replicas(spec: str, flag: str = "--replicas") -> dict[int, int]:
    """``stage1=2,stage3=3`` (or bare ``1=2,3=3``) -> {1: 2, 3: 3}.
    Shared by ``--replicas`` (stage -> R) and ``--device-map``
    (stage -> jax device index); ``flag`` names the error."""
    out: dict[int, int] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        k, _, v = part.partition("=")
        if not v:
            raise SystemExit(f"{flag}: {part!r} is not stageK=N")
        k = k.strip().lower()
        if k.startswith("stage"):
            k = k[len("stage"):]
        try:
            out[int(k)] = int(v)
        except ValueError:
            raise SystemExit(f"{flag}: {part!r} is not stageK=N")
    return out


def _chain_inputs(in_spec, batch: int, count: int) -> list:
    """Deterministic input frames matching the entry boundary's spec
    (integer specs get token ids — the MoE/GPT families embed them)."""
    rng = np.random.default_rng(0)
    if np.issubdtype(np.dtype(in_spec.dtype), np.integer):
        return [rng.integers(0, 100, (batch,) + in_spec.shape)
                .astype(in_spec.dtype) for _ in range(count)]
    return [rng.standard_normal((batch,) + in_spec.shape)
            .astype(np.float32) for _ in range(count)]


def _cmd_chain_dag(args, graph, params) -> None:
    """``chain --dag`` / ``chain --topology FILE``: deploy the branch-
    parallel stage graph — one OS process per topology vertex, parallel
    branches concurrent between a broadcast fork and an all-paths join
    (docs/TRANSPORT.md)."""
    import jax

    from .runtime.node import run_dag_chain
    from .runtime.topology import ChainTopology

    if args.replicas:
        raise SystemExit(
            "chain --dag: replicas do not compose with a branched "
            "topology (a branch hop touching a replicated stage is "
            "rejected like any fan hop); drop --replicas")
    if args.hop_tiers:
        raise SystemExit(
            "chain --dag: hop tiers do not compose with a branched "
            "topology — every branch fan-out/join hop is wire-framed "
            "by design")
    if args.cuts:
        raise SystemExit(
            "chain --dag: --cuts is the linear planner's input; the "
            "DAG topology comes from the solver (or --topology FILE)")
    dag_doc = None
    if args.topology:
        with open(args.topology) as f:
            topo = ChainTopology.from_json(json.load(f))
    else:
        from .plan import StageCostModel
        from .plan.dag import solve_dag
        dag = solve_dag(graph, StageCostModel(graph, batch=args.batch),
                        num_nodes=args.nodes or args.stages)
        dag_doc = dag.to_json()
        topo = ChainTopology.from_json(dag.topology_json())
    from .graph.analysis import max_activation_bytes
    _apply_sock_buf(args, auto_bytes=max_activation_bytes(
        graph, [v.output for v in topo.vertices[:-1]
                if v.output in graph.nodes], batch=args.batch))
    in_spec = graph.out_spec(topo.entry.inputs[0])
    xs = _chain_inputs(in_spec, args.batch, args.count)
    _start_prom(args, "chain")
    stats: list = []
    t0 = time.perf_counter()
    outs = run_dag_chain(graph, params, xs, topology=topo,
                         batch=args.batch, codec=args.codec,
                         rx_depth=args.rx_depth, tx_depth=args.tx_depth,
                         inflight=args.inflight, stats_out=stats,
                         trace_sample_every=args.trace_sample)
    dt = time.perf_counter() - t0
    fwd = jax.jit(graph.apply)
    worst = max(float(np.abs(np.asarray(fwd(params, x)) - y).max())
                for x, y in zip(xs, outs))
    row = {
        "metric": f"{args.model}_{len(topo)}proc_dag_chain",
        "value": round(len(xs) * args.batch / dt, 3),
        "unit": "inferences/sec",
        "stages": len(topo),
        "labels": [v.label for v in topo.vertices],
        "forks": sum(1 for v in topo.vertices if v.fan == "broadcast"),
        "joins": sum(1 for v in topo.vertices if v.join >= 2),
        "codec": args.codec,
        "overlap": not args.no_overlap,
        "max_abs_err_vs_single_program": worst,
    }
    if dag_doc is not None:
        row["predicted_bottleneck_ms"] = dag_doc["bottleneck_ms"]
        row["predicted_critical_path_ms"] = dag_doc["critical_path_ms"]
        row["parallel_regions"] = dag_doc["parallel_regions"]
    print(json.dumps(row))
    _obs_finish(args)


def cmd_chain(args):
    import jax

    from . import partition
    from .runtime.node import run_chain

    _obs_begin(args)
    graph = _get_model(args.model)
    params = graph.init(jax.random.key(0))
    if args.dag or args.topology:
        _cmd_chain_dag(args, graph, params)
        return
    cuts = args.cuts.split(",") if args.cuts else None
    if cuts is not None and args.balance != "flops":
        raise SystemExit(f"--cuts and --balance {args.balance} conflict: "
                         "explicit cuts leave nothing to balance")
    if cuts is None and args.stages:
        # same pre-validation as plan/partition: name the merge nodes
        # locking the cuts instead of dying deep in the cut search
        from .graph.analysis import linear_cut_shortage
        shortage = linear_cut_shortage(graph, args.stages)
        if shortage:
            raise SystemExit(
                f"chain: {shortage.replace('plan --dag', 'chain --dag')}")
    stages = partition(graph, cuts, num_stages=args.stages,
                       objective="bottleneck"
                       if cuts is None and args.balance == "bottleneck"
                       else "quantile")
    # size every data socket's kernel buffers to the fattest boundary
    # frame this partition ships (overridable with --sock-buf)
    from .graph.analysis import max_activation_bytes
    _apply_sock_buf(args, auto_bytes=max_activation_bytes(
        graph, [s.output_name for s in stages[:-1]], batch=args.batch))
    in_spec = stages[0].in_spec
    rng = np.random.default_rng(0)
    xs = [rng.standard_normal((args.batch,) + in_spec.shape)
          .astype(np.float32) for _ in range(args.count)]

    replicas = _parse_replicas(args.replicas)
    hop_tiers = [t for t in args.hop_tiers.split(",") if t] or None
    device_map = _parse_replicas(args.device_map, "--device-map") or None
    _start_prom(args, "chain")
    stats: list = []
    t0 = time.perf_counter()
    outs = run_chain(stages, params, xs, batch=args.batch, codec=args.codec,
                     in_band=args.in_band, overlap=not args.no_overlap,
                     rx_depth=args.rx_depth, tx_depth=args.tx_depth,
                     inflight=args.inflight, replicas=replicas or None,
                     hop_tiers=hop_tiers, tier=args.tier,
                     devices=args.devices, device_map=device_map,
                     stats_out=stats,
                     trace_sample_every=args.trace_sample,
                     failover=args.failover,
                     journal_dir=args.journal_dir or None)
    dt = time.perf_counter() - t0

    fwd = jax.jit(graph.apply)
    worst = max(float(np.abs(np.asarray(fwd(params, x)) - y).max())
                for x, y in zip(xs, outs))
    # the NEGOTIATED transport tier per INTER-stage hop (stage order,
    # one entry per deployed hop — a replicated stage's fan is one tcp
    # policy) plus the last stage's result-hop tier, so bench
    # trajectories distinguish TCP-bound from colocated/fused runs
    tier_of: dict[int, str] = {}
    for s in stats:
        if s.get("stage") is not None:
            tier_of.setdefault(int(s["stage"]), s.get("tier"))
    order = sorted(tier_of)
    # the DEPLOYED stage count: device-tier fusion merges stages before
    # spawn, so the metric name / stage count must describe what ran —
    # a fused single-program row labeled "3proc" would be exactly the
    # TCP-vs-fused confusion the hop_tiers field exists to prevent
    n_deployed = len(order) or len(stages)
    row = {
        "metric": f"{args.model}_{n_deployed}proc_chain",
        "value": round(len(xs) * args.batch / dt, 3),
        "unit": "inferences/sec",
        "stages": n_deployed, "codec": args.codec,
        "overlap": not args.no_overlap,
        "hop_tiers": [tier_of[k] for k in order[:-1]],
        "result_tier": tier_of[order[-1]] if order else None,
        "max_abs_err_vs_single_program": worst,
    }
    if n_deployed != len(stages):
        row["stages_requested"] = len(stages)
    # node-side MFU accounting (obs/capacity.py): only present when a
    # stage reported an honest figure (known chip peak) — never 0.0
    # stand-ins on hosts where the peak is unknowable
    mfu_of = {int(s["stage"]): s["mfu"] for s in stats
              if s.get("stage") is not None and s.get("mfu") is not None}
    if mfu_of:
        row["stage_mfu"] = {f"stage{k}": round(v, 4)
                            for k, v in sorted(mfu_of.items())}
    if args.emit_calibration:
        from .plan.calibrate import CalibrationError, fit_from_stats
        from .utils import hw
        try:
            gen = hw.identify_chip(jax.devices()[0])
        except Exception:  # noqa: BLE001 — no backend
            gen = "unknown"
        try:
            cal = fit_from_stats(graph,
                                 [s.output_name for s in stages[:-1]],
                                 stats, batch=args.batch, gen=gen)
        except CalibrationError as e:
            raise SystemExit(f"--emit-calibration: {e}") from e
        cal.save(args.emit_calibration)
        row["calibration"] = args.emit_calibration
    if replicas:
        row["replicas"] = {f"stage{k}": r
                           for k, r in sorted(replicas.items())}
        # per-replica aggregation: how the round-robin actually split
        row["per_node_processed"] = [
            {"stage": s.get("stage"), "replica": s.get("replica"),
             "processed": s.get("processed")} for s in stats]
    print(json.dumps(row))
    _obs_finish(args)


def _render_monitor(rows, bottleneck, flags, offsets, *, clear: bool,
                    drift=()):
    """One refresh of the top-style monitor table (human mode)."""
    tty = sys.stdout.isatty()
    if clear and tty:
        print("\x1b[2J\x1b[H", end="")
    print(f"{'STAGE':>5} {'BR':>3} {'REP':>3} {'TIER':>5} {'INF/S':>8} "
          f"{'P50MS':>9} "
          f"{'P95MS':>9} {'P99MS':>9} {'HS50':>7} {'DISP':>7} "
          f"{'DEV':>7} {'MEM':>7} {'MFU%':>6} "
          f"{'PRED':>9} {'MEAS':>9} {'ERR%':>7} "
          f"{'RXQ':>4} {'TXQ':>4} "
          f"{'RX^':>4} {'TX^':>4} {'INF':>4} {'RX B/S':>11} "
          f"{'TX B/S':>11} {'DONE':>8}  ADDR")
    for r in rows:
        stage = "-" if r["stage"] is None else str(r["stage"])
        rep = "-" if r["replica"] is None else str(r["replica"])
        # branched topologies: bJ = this row rides branch path J of a
        # fork/join region, jP = this row is the P-path join — so the
        # bottleneck highlight names a branch, not a flattened index
        if r.get("branch") is not None:
            br = f"b{r['branch']}"
        elif (r.get("join") or 0) >= 2:
            br = f"j{r['join']}"
        else:
            br = "-"
        # a "!" marks a DEGRADED hop (this node offered a colocated
        # tier, fell back, and is STILL riding tcp) — distinguishable
        # from a hop that rides tcp because nothing better was ever
        # offered; a later successful renegotiation clears the mark
        # even though the lifetime fallback count stays nonzero
        tier = r.get("tier") or "-"
        tier = tier[:4] + "!" \
            if r.get("tier_fallbacks") and tier == "tcp" else tier[:5]
        p = r["infer_ms"]
        # host-sync p50: "-" when the row recorded ZERO samples — an
        # ici (device-resident) hop's proof mark
        hs = r.get("host_sync_ms") or {}
        hs50 = "-" if not hs.get("count") else f"{hs.get('p50', 0):.3f}"
        # phase X-ray p50s (obs/profile.py): dispatch (the jit call
        # returning) / device (block_until_ready) next to HS50 — "-"
        # at zero samples, same convention
        dp = r.get("dispatch_ms") or {}
        disp = "-" if not dp.get("count") else f"{dp.get('p50', 0):.3f}"
        dv = r.get("device_ms") or {}
        dev = "-" if not dv.get("count") else f"{dv.get('p50', 0):.3f}"
        # live device-array megabytes — "-" from a process that never
        # loaded jax (None on the wire; a fake 0 would be a lie)
        mem = "-" if r.get("mem_bytes") is None \
            else f"{r['mem_bytes'] / 1e6:.1f}M"
        # MFU is "-" unless the node reported an HONEST figure (known
        # chip peak + deployed capacity) — a fabricated 0.0 would be
        # indistinguishable from a real idle chip
        mfu = "-" if r.get("mfu") is None else f"{r['mfu'] * 100:.1f}"
        # predicted-vs-measured service audit (obs/capacity.py): only
        # rendered when monitor has --plan and --model to predict from
        pred = "-" if r.get("pred_ms") is None else f"{r['pred_ms']:.3f}"
        meas = "-" if r.get("meas_ms") is None else f"{r['meas_ms']:.3f}"
        errp = "-" if r.get("err") is None else f"{r['err'] * 100:+.1f}"
        line = (f"{stage:>5} {br:>3} {rep:>3} {tier:>5} "
                f"{r['throughput_per_s']:>8.1f} "
                f"{p['p50']:>9.3f} {p['p95']:>9.3f} {p['p99']:>9.3f} "
                f"{hs50:>7} {disp:>7} {dev:>7} {mem:>7} "
                f"{mfu:>6} {pred:>9} {meas:>9} {errp:>7} "
                f"{r['rx_q']:>4.0f} {r['tx_q']:>4.0f} "
                f"{r['rx_hi']:>4.0f} {r['tx_hi']:>4.0f} "
                f"{r['inflight']:>4.0f} {r['rx_bytes_per_s']:>11.0f} "
                f"{r['tx_bytes_per_s']:>11.0f} {r['processed']:>8}  "
                f"{r['addr'] or ''}")
        mark = (bottleneck is not None and r["stage"] == bottleneck)
        if not r["alive"]:
            line += "  [DEAD]"
        if mark:
            line = f"\x1b[7m{line}\x1b[0m" if tty \
                else line + "  <- bottleneck"
        print(line)
    for f in flags:
        print(f"straggler: stage {f.stage} [{f.reason}] measured "
              f"{f.measured_ms:.3f} ms vs planned {f.expected_ms:.3f} ms "
              f"(x{f.ratio:.2f}, {f.intervals} intervals)")
    for f in drift:
        print(f"model_drift: stage {f.stage} predicted "
              f"{f.predicted_ms:.3f} ms vs measured "
              f"{f.measured_ms:.3f} ms ({f.rel_err * 100:+.1f}%, "
              f"{f.intervals} intervals)")
    if offsets:
        worst = max(abs(v["offset_us"]) for v in offsets.values())
        print(f"clock: {len(offsets)} nodes aligned "
              f"(worst offset {worst / 1e3:.3f} ms)")
    sys.stdout.flush()


def _parse_tenant_specs(specs) -> list:
    """``name=weight[:priority[:deadline_ms]]`` (repeatable) ->
    TenantConfig list."""
    from .serve import TenantConfig
    out = []
    for spec in specs or []:
        name, sep, rest = spec.partition("=")
        if not sep or not name:
            raise SystemExit(f"--tenant: {spec!r} is not "
                             f"name=weight[:priority[:deadline_ms]]")
        parts = rest.split(":")
        try:
            out.append(TenantConfig(
                name=name, weight=float(parts[0] or 1.0),
                priority=int(parts[1]) if len(parts) > 1 and parts[1]
                else 0,
                deadline_ms=float(parts[2])
                if len(parts) > 2 and parts[2] else None))
        except ValueError as e:
            raise SystemExit(f"--tenant {spec!r}: {e}")
    return out


def cmd_serve(args):
    """The serving front door (docs/SERVING.md): accept many concurrent
    client streams, admit under per-tenant weighted-fair queuing with
    SLO-aware shedding, coalesce admitted samples across tenants into
    dynamic microbatches sized by the planner's latency budget, and
    multiplex them onto one deployed chain (tensor mode) or a
    continuous-batching decode engine (--workload decode)."""
    import threading

    import jax

    from . import partition
    from .serve import ServeFrontDoor
    from .serve.frontdoor import ChainBackend

    graph = _get_model(args.model)
    params = graph.init(jax.random.key(0))
    tenants = _parse_tenant_specs(args.tenant)
    _start_prom(args, "serve")
    # request-scoped tracing composes with serving (docs/SERVING.md):
    # --trace-out enables the tracer, --trace-sample N samples whole
    # REQUESTS 1-in-N (every frame of a sampled request traces end to
    # end across the front door AND every stage process)
    _obs_begin(args, process="serve")
    ext_addrs: list[str] = []

    if args.workload == "decode":
        from .serve import ContinuousBatchEngine
        if "lm_head" not in graph.nodes:
            raise SystemExit(f"{args.model} is not a decoder model; "
                             "--workload decode needs a gpt* family")
        width = args.width or 4
        engine = ContinuousBatchEngine(graph, params,
                                       num_stages=args.stages,
                                       width=width)
        door = ServeFrontDoor(
            engine=engine, listen=args.listen, tenants=tenants,
            decode_defaults={"max_new_tokens": args.max_new})
        cleanup = lambda: None  # noqa: E731
    else:
        cuts = args.cuts.split(",") if args.cuts else None
        stages = partition(graph, cuts, num_stages=args.stages)
        cut_names = [s.output_name for s in stages[:-1]]
        width = args.width
        if args.budget_ms:
            # dynamic-microbatch width from the planner's cost model:
            # the largest frame batch whose slowest stage stays inside
            # the per-stage latency budget
            from .plan import max_batch_within_budget
            cm = _cost_model(args, graph)
            width = max_batch_within_budget(
                graph, cut_names, cm, args.budget_ms,
                cap=args.max_width)
            print(f"serve: width {width} from --budget-ms "
                  f"{args.budget_ms:g}", file=sys.stderr, flush=True)
        width = width or 4
        hop_codecs = [c for c in args.hop_codecs.split(",") if c] or None
        if args.nodes:
            from .runtime.node import ChainDispatcher
            addrs = [a for a in args.nodes.split(",") if a]
            if len(addrs) != len(stages):
                raise SystemExit(f"{len(stages)} stages but "
                                 f"{len(addrs)} --nodes")
            disp = ChainDispatcher(addrs[0], codec=args.codec)
            disp.deploy(stages, params, addrs, batch=width,
                        codecs=hop_codecs)
            ext_addrs = addrs
            from .obs import tracer
            if tracer().enabled:
                # external stage processes: re-anchor their tracers so
                # a sampled request's cross-process waterfall lands on
                # one Perfetto timeline (the dispatcher edge of clock
                # alignment, docs/OBSERVABILITY.md)
                disp.align_clocks(addrs)
            cleanup = lambda: None  # noqa: E731 — nodes are external
        else:
            # self-contained deployment: thread-per-stage nodes in this
            # process (run `defer_tpu node` per host + --nodes for a
            # real multi-process chain)
            from .runtime.node import ChainDispatcher, StageNode
            nodes = [StageNode(None, "127.0.0.1:0", None)
                     for _ in stages]
            addrs = [f"127.0.0.1:{n.address[1]}" for n in nodes]
            threads = [threading.Thread(target=n.serve, daemon=True)
                       for n in nodes]
            for t in threads:
                t.start()
            disp = ChainDispatcher(addrs[0], codec=args.codec)
            disp.deploy(stages, params, addrs, batch=width,
                        codecs=hop_codecs)

            def cleanup(_threads=threads):
                for t in _threads:
                    t.join(timeout=10)
        backend = ChainBackend(disp, width,
                               tuple(stages[0].in_spec.shape),
                               window=args.window,
                               trace_sample_every=args.trace_sample)
        door = ServeFrontDoor(backend=backend, listen=args.listen,
                              tenants=tenants,
                              gather_s=args.gather_ms / 1e3)
    door.start()
    if args.journal_dir:
        # the front door is a fleet member too: its admission/shed
        # events and pressure snapshots belong in the black box
        from .obs import start_journal

        def _serve_row(_door=door):
            return {"pressure": _door.pressure(),
                    "stats": _door.stats()}

        start_journal(args.journal_dir, "serve", snapshot_fn=_serve_row)
    print(json.dumps({"serving": f"{door.address[0]}:{door.address[1]}",
                      "mode": door.mode, "width": door.width,
                      "model": args.model, "stages": args.stages}),
          flush=True)
    try:
        deadline = time.monotonic() + args.seconds if args.seconds > 0 \
            else None
        while deadline is None or time.monotonic() < deadline:
            time.sleep(0.5 if deadline is None
                       else min(0.5, max(0.0,
                                         deadline - time.monotonic())))
            # a dead backend/engine loop must fail the process, not
            # silently serve nothing until the timer runs out
            door.healthcheck()
    except KeyboardInterrupt:
        pass
    except BaseException as e:
        if args.journal_dir:
            # a dead backend/engine is exactly what the black box is
            # for: final-spill, then bundle synchronously before dying
            from .obs import maybe_autopsy, stop_journal
            stop_journal()
            maybe_autopsy(f"serve: {type(e).__name__}: {e}",
                          journal_dir=args.journal_dir, sync=True,
                          delay_s=0.0)
        raise
    finally:
        from .obs import tracer
        if tracer().enabled and ext_addrs:
            # stitch the external stage processes' spans in while they
            # are still alive (in-process thread nodes already share
            # this tracer, so only --nodes chains need the collection)
            try:
                disp.collect_trace(ext_addrs)
            except Exception as e:  # noqa: BLE001 — advisory
                print(f"serve: trace collection failed: {e!r}",
                      file=sys.stderr, flush=True)
        door.stop()
        cleanup()
        if args.journal_dir:
            from .obs import stop_journal
            stop_journal()
        _obs_finish(args)
        print(json.dumps({"final_stats": door.stats()}), flush=True)


def cmd_postmortem(args):
    """Assemble a forensics bundle from the on-disk black-box journals
    under a ``--journal-dir`` — no live process required; the journals
    of dead (kill -9'd) processes are the whole point
    (docs/OBSERVABILITY.md, "Black box & postmortem")."""
    from .obs import collect_postmortem

    bundle = collect_postmortem(args.dir, out_dir=args.out or None,
                                reason=args.reason, last_s=args.last_s)
    for w in bundle["warnings"]:
        print(f"postmortem: WARNING: {w}", file=sys.stderr, flush=True)
    verdict = bundle["verdict"] or {}
    print(json.dumps({
        "out_dir": bundle["out_dir"],
        "procs": [p["proc"] for p in bundle["procs"]],
        "events": len(bundle["timeline"]),
        "events_dropped": bundle["events_dropped"],
        "warnings": len(bundle["warnings"]),
        "first_fault": verdict.get("first_fault"),
        "evidence": verdict.get("evidence"),
        "casualties": [c["proc"] for c in verdict.get("casualties", [])],
    }, default=str), flush=True)


def cmd_serve_client(args):
    """Load-generating client: play a deterministic open-loop Poisson
    arrival trace (optional burst phases) against a front door and
    print the latency/shed summary (docs/SERVING.md)."""
    from .serve import LoadGenerator, ServeClient, poisson_trace

    host, _, port = args.connect.rpartition(":")
    bursts = []
    for spec in args.burst or []:
        t0, t1, mult = spec.split(":")
        bursts.append((float(t0), float(t1), float(mult)))
    offsets = poisson_trace(args.rate, args.seconds, seed=args.seed,
                            bursts=bursts or None)
    rng = np.random.default_rng(args.seed)
    hello = {}
    if args.max_new:
        hello["max_new_tokens"] = args.max_new
    if args.prompt_len:
        samples = [rng.integers(0, args.vocab, (args.prompt_len,))
                   .astype(np.int32) for _ in range(max(1, len(offsets)))]
    else:
        shape = tuple(int(d) for d in args.sample_shape.split(",") if d)
        samples = [rng.standard_normal(shape).astype(np.float32)
                   for _ in range(max(1, min(64, len(offsets))))]
    client = ServeClient(host or "127.0.0.1", int(port), args.tenant,
                         weight=args.weight, priority=args.priority,
                         deadline_ms=args.deadline_ms or None, **hello)
    print(json.dumps(LoadGenerator(client, samples, offsets).run()),
          flush=True)


def _render_serve_stats(doc: dict) -> None:
    """Per-tenant serving columns of the monitor (docs/SERVING.md)."""
    print(f"serve: mode={doc.get('mode')} width={doc.get('width')} "
          f"frames={doc.get('frames')} queued={doc.get('queued')} "
          f"inflight={doc.get('inflight')} service~"
          f"{doc.get('service_estimate_ms')}ms")
    print(f"{'TENANT':>12} {'W':>5} {'PRI':>3} {'QUEUED':>6} {'ADM':>7} "
          f"{'SHED':>6} {'DONE':>7} {'QDELAY P50':>11} {'P99 MS':>8} "
          f"{'SLO%':>6}")
    attrib = doc.get("attribution") or {}
    for name, r in (doc.get("tenants") or {}).items():
        qd = r.get("queue_delay_s") or {}
        p50 = (qd.get("p50", 0.0) or 0.0) * 1e3 if qd.get("count") else 0.0
        p99 = (qd.get("p99", 0.0) or 0.0) * 1e3 if qd.get("count") else 0.0
        # SLO attainment: fraction of DELIVERED units inside the
        # tenant's deadline_ms ("-" = no deadline / nothing scored yet)
        att = r.get("slo_attainment")
        att_s = "-" if att is None else f"{att * 100:.1f}"
        print(f"{name:>12} {r.get('weight', 1):>5.1f} "
              f"{r.get('priority', 0):>3} {r.get('queued', 0):>6} "
              f"{r.get('admitted', 0):>7} {r.get('shed', 0):>6} "
              f"{r.get('completed', 0):>7} {p50:>11.3f} {p99:>8.3f} "
              f"{att_s:>6}")
        # where the tenant's latency goes: the door's always-on
        # attribution buckets (p50 ms per bucket, docs/OBSERVABILITY.md)
        buckets = attrib.get(name)
        if buckets and (buckets.get("e2e") or {}).get("count"):
            parts = " ".join(
                f"{k}={((buckets.get(k) or {}).get('p50', 0.0)):.2f}"
                for k in ("admission", "gather", "chain", "result_edge"))
            print(f"{'':>12}   p50ms: {parts} "
                  f"e2e={(buckets['e2e'].get('p50', 0.0)):.2f}")


def cmd_monitor(args):
    """Live chain observability: subscribe to every node's obs_push
    stream (passively estimating each node's clock offset; --align to
    actively re-anchor), render a refreshing per-stage/per-replica
    table with the bottleneck stage highlighted — or --json lines for
    machine consumption.  With --plan
    (a ``plan --json`` file) the straggler detector compares live
    service estimates against the plan and, when --model is also given,
    a flagged stage triggers a replan suggestion."""
    from .obs.cluster import (ClusterView, StragglerDetector,
                              expected_stage_ms)

    addrs = [a for a in (args.nodes or "").split(",") if a]
    if not addrs and not args.serve:
        raise SystemExit("monitor requires --nodes host:port[,...] "
                         "and/or --serve host:port")
    # --follow is a pure event tail (implies --events); --kind narrows
    # both the tail and the table's event footer to the listed kinds
    kind_filter = {k for k in (getattr(args, "kind", "") or ""
                               ).split(",") if k}
    if kind_filter:
        from .obs.events import EVENT_KINDS
        unknown = kind_filter - set(EVENT_KINDS)
        if unknown:
            raise SystemExit(f"--kind: unknown event kind(s) "
                             f"{sorted(unknown)}; known: "
                             f"{sorted(EVENT_KINDS)}")
    follow = bool(getattr(args, "follow", False))
    if follow:
        args.events = True
    detector = plan = graph = auditor = None
    if args.plan:
        from .plan import plan_from_json
        with open(args.plan) as f:
            plan = plan_from_json(json.load(f))
        detector = StragglerDetector(expected_stage_ms(plan),
                                     factor=args.factor,
                                     sustain=args.sustain)
        if args.model:
            graph = _get_model(args.model)
            # drift auditor (obs/capacity.py): per-stage service
            # predictions ALIGNED with what the view measures (max of
            # compute / inbound decode / outbound encode, codec-only —
            # plan.calibrate.predict_stage_service_s), scored against
            # the window-bounded live estimates every interval.  The
            # cost model is the plan's own (calibrated constants
            # round-trip through plan JSON); --calibrated overlays a
            # newer artifact
            from .obs.capacity import DriftAuditor
            from .plan.calibrate import predict_stage_service_s
            from .plan.replan import cost_model_from_plan
            cost = cost_model_from_plan(graph, plan)
            if getattr(args, "calibrated", ""):
                from .plan import CalibratedConstants
                cost = CalibratedConstants.load(
                    args.calibrated).apply(cost)
            pred_ms = [s * 1e3 for s in predict_stage_service_s(
                graph, plan.cuts, plan.codecs, cost)]
            auditor = DriftAuditor(pred_ms,
                                   threshold=args.drift_threshold,
                                   sustain=args.sustain)
    view = ClusterView()
    if addrs:
        # follow mode survives node restarts: the failover supervisor
        # respawns a killed replica on its old port, so the reader
        # redials with connect_retry's jittered backoff instead of
        # exiting on the first dead socket (merge_events below dedups
        # any resumed-stream overlap on the (proc, seq) key)
        view.connect(addrs, interval_ms=args.interval_ms,
                     align_clocks=args.align,
                     timeout_s=args.connect_timeout,
                     reconnect=follow)
    door_ev_cursor = 0
    door_ev_dropped = 0
    last_dropped = 0
    try:
        i = 0
        while True:
            time.sleep(args.interval_ms / 1e3)
            i += 1
            events = None
            if args.events:
                # the merged flight-recorder log, incremental: node
                # events arrive on the obs_push stream (drained from
                # the view), the front door's over an events_since
                # observer round-trip (docs/OBSERVABILITY.md)
                from .obs.events import merge_events
                batch = view.take_events()
                if args.serve:
                    from .serve.client import fetch_events
                    h, _, p = args.serve.rpartition(":")
                    try:
                        rep = fetch_events(
                            h or "127.0.0.1", int(p),
                            cursor=door_ev_cursor,
                            timeout_s=args.connect_timeout)
                        batch += rep.get("events") or []
                        door_ev_cursor = rep.get("cursor",
                                                 door_ev_cursor)
                        door_ev_dropped = rep.get("dropped", 0)
                    except (OSError, ConnectionError):
                        pass
                events = merge_events(batch)
                if kind_filter:
                    events = [e for e in events
                              if e["kind"] in kind_filter]
            if follow:
                # tail mode: one line per merged event as it arrives —
                # a fleet-wide recompile/failover storm watched live
                # instead of re-polled; no table, no clearing
                for ev in events or []:
                    if args.json:
                        print(json.dumps(ev), flush=True)
                    else:
                        data = " ".join(
                            f"{k}={v}" for k, v in
                            sorted(ev["data"].items()))
                        print(f"{ev['t_us'] / 1e6:16.6f} "
                              f"[{ev['kind']:>14}] {ev['proc']}"
                              f"#{ev['seq']} {data}", flush=True)
                # evidence-gap footer: a tail with ring evictions is
                # NOT the whole story — say so when the count grows
                dropped = view.events_dropped + door_ev_dropped
                if dropped > last_dropped:
                    print(f"event: WARNING {dropped} events dropped "
                          f"ring-wide — the merged log has gaps "
                          f"(raise DEFER_EVENTS_CAP)", flush=True)
                    last_dropped = dropped
                if args.iterations and i >= args.iterations:
                    return
                continue
            serve_doc = None
            if args.serve:
                from .serve.client import fetch_stats
                host, _, port = args.serve.rpartition(":")
                try:
                    serve_doc = fetch_stats(host or "127.0.0.1",
                                            int(port),
                                            timeout_s=args.connect_timeout)
                except (OSError, ConnectionError) as e:
                    serve_doc = {"error": repr(e)}
            rows = view.rows()
            bott = view.bottleneck()
            flags = detector.observe(view) if detector is not None else []
            drift_flags = []
            if auditor is not None:
                drift_flags = auditor.observe(view)
                for r in rows:
                    audit = auditor.last.get(r.get("stage"))
                    if audit:
                        r.update(audit)
            suggestion = err = None
            if flags and graph is not None:
                try:
                    suggestion = detector.suggest(view, graph, plan)
                except Exception as e:  # noqa: BLE001 — advisory
                    err = repr(e)
            if args.json:
                doc = {"iteration": i, "bottleneck": bott, "rows": rows,
                       "stragglers": [f.to_json() for f in flags],
                       "drift": [f.to_json() for f in drift_flags],
                       "clock_offsets": {
                           a: round(v["offset_us"], 1)
                           for a, v in view.clock_offsets.items()}}
                if events is not None:
                    doc["events"] = events
                    doc["events_dropped"] = (view.events_dropped
                                             + door_ev_dropped)
                if serve_doc is not None:
                    serve_doc.pop("cmd", None)
                    doc["serve"] = serve_doc
                if suggestion is not None:
                    doc["replan"] = suggestion.to_json()
                elif err is not None:
                    doc["replan_error"] = err
                print(json.dumps(doc), flush=True)
            else:
                _render_monitor(rows, bott, flags, view.clock_offsets,
                                clear=i > 1, drift=drift_flags)
                if events:
                    for ev in events[-16:]:
                        data = " ".join(f"{k}={v}" for k, v in
                                        sorted(ev["data"].items()))
                        print(f"event: [{ev['kind']}] {ev['proc']}"
                              f"#{ev['seq']} {data}")
                if args.events:
                    # evidence-gap footer rides EVERY --events refresh
                    # (not only ticks that happened to render events):
                    # a nonzero total means the merged log has holes
                    dropped = view.events_dropped + door_ev_dropped
                    if dropped:
                        print(f"event: ({dropped} dropped ring-wide — "
                              f"merged log has gaps; raise "
                              f"DEFER_EVENTS_CAP)")
                if serve_doc is not None:
                    _render_serve_stats(serve_doc)
                if suggestion is not None:
                    s = suggestion
                    print(f"replan: moved={s.moved} predicted "
                          f"improvement {s.predicted_improvement:.2f}x "
                          f"(new cuts {','.join(s.new_plan.cuts) or '-'}"
                          + (f", replicas "
                             f"{getattr(s.new_plan, 'replicas', None)}"
                             if getattr(s.new_plan, "replicas", None)
                             else "") + ")")
                elif err is not None:
                    print(f"replan failed: {err}")
            if args.iterations and i >= args.iterations:
                return
    except KeyboardInterrupt:
        pass
    finally:
        view.close()


def cmd_profile(args):
    """Attach to a running chain's nodes for N seconds and produce the
    stage-interior X-ray (docs/OBSERVABILITY.md §Profiling): per node a
    ``profile_start``/``profile_stop`` bracket over the existing ctrl
    connection (the obs_subscribe pattern — no new ports) whose stop
    reply carries the window's DELTA phase breakdown
    (dispatch/device/host_sync counts + summed seconds), recompiles,
    and live device memory; optionally the sampled spans, dumped and
    clock-shifted onto THIS process's timeline (passive: the nodes'
    own anchors are never touched) and exported as one merged Perfetto
    trace.  Machine-readable JSON on stdout (or --out)."""
    import os

    from .obs import tracer
    from .obs.cluster import estimate_clock_offset
    from .runtime.node import _connect_retry, _parse_hostport
    from .transport.framed import (K_CTRL, recv_expect, send_ctrl,
                                   send_end)

    addrs = [a for a in (args.nodes or "").split(",") if a]
    if not addrs:
        raise SystemExit("profile requires --nodes host:port[,...]")
    want_spans = args.spans or bool(args.trace_out)
    tr = tracer()
    conns: dict = {}
    offsets: dict = {}
    reports: dict = {}
    try:
        for addr in addrs:
            s = _connect_retry(*_parse_hostport(addr),
                               timeout_s=args.connect_timeout)
            conns[addr] = s
            # passive min-RTT offset estimate per node: dumped spans
            # are shifted HERE — an observer must not re-anchor spans
            # a dispatcher may already have aligned
            offsets[addr] = estimate_clock_offset(s)
        if want_spans:
            tr.enabled = True
            tid = tr.start_trace()
            tr.process = "profiler"
            for addr, s in conns.items():
                send_ctrl(s, {"cmd": "trace", "trace_id": tid,
                              "sample_every": max(0, args.sample_every)})
        for addr, s in conns.items():
            msg: dict = {"cmd": "profile_start"}
            if args.jax_trace_dir:
                # per-node subdir: the node runs jax.profiler.trace
                # locally where the backend supports it
                msg["jax_trace_dir"] = os.path.join(
                    args.jax_trace_dir, addr.replace(":", "_"))
            send_ctrl(s, msg)
            rep = recv_expect(s, K_CTRL)
            if rep.get("cmd") != "profile_started":
                raise SystemExit(f"profile_start on {addr} refused: "
                                 f"{rep.get('error', rep)}")
        time.sleep(args.seconds)
        for addr, s in conns.items():
            send_ctrl(s, {"cmd": "profile_stop"})
            rep = recv_expect(s, K_CTRL)
            if rep.get("cmd") != "profile_report":
                raise SystemExit(f"profile_stop on {addr} failed: "
                                 f"{rep.get('error', rep)}")
            reports[addr] = rep["report"]
        if want_spans:
            n_spans = 0
            for addr, s in conns.items():
                send_ctrl(s, {"cmd": "trace_dump"})
                doc = recv_expect(s, K_CTRL)
                spans = doc.get("spans") or []
                off = int(round(offsets[addr]["offset_us"]))
                for sp in spans:
                    sp["ts_us"] -= off
                n_spans += len(spans)
                tr.ingest(spans)
            if n_spans == 0 and args.sample_every >= 1:
                # 1-in-N waterfall sampling keys off the wire sequence
                # stamp so every stage samples the SAME frames; a chain
                # whose dispatcher doesn't stamp (trace_sample_every=0)
                # carries no seqs and N>=1 matches nothing.  Say so
                # instead of silently writing an empty trace.
                print(f"profile: WARNING: --sample-every "
                      f"{args.sample_every} returned zero spans — "
                      f"1-in-N sampling needs sequence-stamped frames "
                      f"(a dispatcher started with trace_sample_every "
                      f">= 1).  Re-run with --sample-every 0 to record "
                      f"every frame on any stream.",
                      file=sys.stderr, flush=True)
        for s in conns.values():
            try:
                send_end(s)
            except OSError:
                pass
    finally:
        for s in conns.values():
            s.close()
    for addr, rep in reports.items():
        ph = rep.get("phases") or {}
        inf = ph.get("infer") or {}
        dsp = ph.get("dispatch") or {}
        if inf.get("sum_s"):
            # the MPK question in one number: how much of the frame
            # wall is host-side dispatch
            rep["dispatch_share"] = round(
                (dsp.get("sum_s") or 0.0) / inf["sum_s"], 4)
        parts = " ".join(
            f"{name}={p['sum_s']:.3f}s/{p['count']}"
            for name, p in ph.items())
        print(f"{rep.get('node', addr)}: {parts} "
              f"recompiles={rep.get('recompiles')} "
              f"mem_bytes={rep.get('mem_bytes')} "
              f"dispatch_share={rep.get('dispatch_share', '-')}",
              file=sys.stderr, flush=True)
    if args.trace_out:
        from .obs import export_chrome_trace
        export_chrome_trace(args.trace_out)
        print(f"profile: merged trace -> {args.trace_out}",
              file=sys.stderr, flush=True)
    doc = {"seconds": args.seconds, "nodes": reports,
           "clock_offsets": {a: round(v["offset_us"], 1)
                             for a, v in offsets.items()}}
    if args.out:
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=2)
            f.write("\n")
        print(f"profile: breakdown -> {args.out}",
              file=sys.stderr, flush=True)
    else:
        print(json.dumps(doc), flush=True)


def cmd_train(args):
    """Pipeline-parallel training demo: synthetic data, cross-entropy,
    prints per-step loss (JSON line at the end)."""
    import optax

    import jax
    import jax.numpy as jnp

    from . import SpmdPipeline, partition, pipeline_mesh
    from .runtime.training import PipelineTrainer

    graph = _get_model(args.model)
    params = graph.init(jax.random.key(0))
    cuts = args.cuts.split(",") if args.cuts else None
    stages = partition(graph, cuts, num_stages=args.stages)
    pipe = SpmdPipeline(stages, params, mesh=pipeline_mesh(len(stages)),
                        microbatch=args.microbatch, chunk=args.chunk,
                        wire=args.wire)
    in_spec, out_spec = pipe.in_spec, pipe.out_spec
    classes = out_spec.shape[-1]

    def ce(logits, labels):
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        return -jnp.mean(jnp.take_along_axis(logp, labels[..., None], -1))

    trainer = PipelineTrainer(pipe, ce, optimizer=optax.adam(args.lr))
    rng = np.random.default_rng(0)
    m = args.chunk - len(stages) + 1
    m = max(m, 1)
    if jnp.issubdtype(in_spec.dtype, jnp.integer):
        xs = rng.integers(0, 64, (m, args.microbatch) + in_spec.shape
                          ).astype(np.float32)
    else:
        xs = rng.standard_normal(
            (m, args.microbatch) + in_spec.shape).astype(np.float32)
    ys = rng.integers(0, classes, (m, args.microbatch))

    losses = []
    for i in range(args.steps):
        t0 = time.perf_counter()
        loss = trainer.step(xs, ys)
        losses.append(round(loss, 4))
        print(f"step {i}: loss {loss:.4f} "
              f"({time.perf_counter() - t0:.2f}s)", file=sys.stderr)
    if args.save:
        trainer.save_checkpoint(args.save)
        print(f"checkpoint -> {args.save}", file=sys.stderr)
    print(json.dumps({"model": args.model, "stages": len(stages),
                      "steps": args.steps, "losses": losses}))


def cmd_generate(args):
    """Pipelined autoregressive generation demo (random prompts)."""
    import jax

    from .runtime.decode import PipelinedDecoder

    graph = _get_model(args.model)
    if "lm_head" not in graph.nodes:
        raise SystemExit(f"{args.model} is not a decoder model; use one of "
                         "the gpt* families")
    params = graph.init(jax.random.key(0))
    vocab = graph.nodes["lm_head"].out_spec.shape[-1]
    dec = PipelinedDecoder(graph, params, num_stages=args.stages,
                           microbatch=args.microbatch,
                           kv_cache=args.kv_cache,
                           weight_dtype=args.weight_dtype or None,
                           beam_width=args.beam)
    rng = np.random.default_rng(args.seed)
    b = args.stages * (args.microbatch // args.beam)
    prompt = rng.integers(0, vocab, (b, args.prompt_len)).astype(np.int32)
    # pass everything through: incompatible combinations (e.g. beam +
    # prefill) surface as the decoder's ValueError instead of a silently
    # different configuration than the JSON record claims
    kw = dict(token_chunk=args.token_chunk, temperature=args.temperature,
              top_k=args.top_k, seed=args.seed, prefill=args.prefill)
    from .obs import REGISTRY, tracer
    dec.generate(prompt, args.new_tokens, **kw)   # compile
    # steady-state exports only: drop the compile run's decode samples
    # and enable tracing for the warm run
    REGISTRY.histogram("decode.dispatch_s").clear()
    REGISTRY.counter("decode.dispatches").n = 0
    _obs_begin(args)
    t0 = time.perf_counter()
    with tracer().span("generate", {"model": args.model,
                                    "new_tokens": args.new_tokens}):
        toks = dec.generate(prompt, args.new_tokens, **kw)   # warm
    dt = time.perf_counter() - t0
    print(json.dumps({
        "model": args.model, "stages": args.stages,
        "batch": b, "prompt_len": args.prompt_len,
        "new_tokens": args.new_tokens, "prefill": args.prefill,
        "kv_cache": args.kv_cache, "beam": args.beam,
        "weight_dtype": args.weight_dtype or "compute",
        "tokens_per_s": round(b * args.new_tokens / dt, 2),
        "first_row": toks[0].tolist(),
    }))
    _obs_finish(args)


def main(argv=None):
    ap = argparse.ArgumentParser(prog="python -m defer_tpu")
    sub = ap.add_subparsers(dest="cmd", required=True)

    sub.add_parser("models", help="list the model zoo")

    p = sub.add_parser("partition", help="show the stage table")
    p.add_argument("--model", required=True)
    p.add_argument("--stages", type=int)
    p.add_argument("--cuts")
    p.add_argument("--balance",
                   choices=["flops", "measured", "bottleneck"],
                   default="flops",
                   help="auto-cut objective: FLOP quantiles (analytic), "
                        "measured-latency quantiles, or the exact comm-"
                        "aware bottleneck solver (docs/PLANNER.md)")
    p.add_argument("--batch", type=int, default=1,
                   help="batch size for measured timing / comm sizing")
    p.add_argument("--json", action="store_true",
                   help="machine-readable output (cuts, stage table, "
                        "plan predictions) instead of the human table")
    p.add_argument("--dot", help="write a DOT graph with stage coloring")
    p.add_argument("--summary", action="store_true")
    _add_cost_flags(p)

    pl = sub.add_parser("plan", help="comm-aware bottleneck partition "
                                     "plan vs the quantile baseline")
    pl.add_argument("--model", required=True)
    pl.add_argument("--stages", type=int)
    pl.add_argument("--batch", type=int, default=1,
                    help="per-hop frame batch for the comm model")
    pl.add_argument("--measured", action="store_true",
                    help="measure per-node seconds on this backend "
                         "instead of the analytic roofline")
    pl.add_argument("--sweep", type=int, metavar="MAX",
                    help="solve every stage count 1..MAX and recommend")
    pl.add_argument("--nodes", type=int, metavar="N",
                    help="hybrid plan for a budget of N processes: "
                         "jointly choose cuts AND per-stage replica "
                         "counts (docs/PLANNER.md)")
    pl.add_argument("--target-ms", type=float, default=0.0,
                    help="bottleneck latency target for the --sweep "
                         "recommendation (fewest stages that meet it)")
    pl.add_argument("--replan", metavar="METRICS_JSON",
                    help="re-solve with measured per-stage seconds from "
                         "a --metrics-out snapshot (telemetry-corrected "
                         "cost model)")
    pl.add_argument("--dag", action="store_true",
                    help="branch-parallel stage GRAPH plan for --nodes N "
                         "processes: parallel branches become concurrent "
                         "sub-pipelines with a broadcast fork and an "
                         "all-paths join; reports bottleneck AND "
                         "critical path vs the best linear plan at the "
                         "same node count, and the JSON carries the "
                         "deployable topology (docs/PLANNER.md)")
    pl.add_argument("--json", action="store_true")
    _add_cost_flags(pl)

    b = sub.add_parser("bench", help="timed pipeline throughput")
    b.add_argument("--model", default="resnet_tiny")
    b.add_argument("--stages", type=int)
    b.add_argument("--cuts")
    b.add_argument("--balance", choices=["flops", "bottleneck"],
                   default="flops",
                   help="auto-cut objective for --stages (bottleneck: "
                        "the comm-aware exact solver)")
    b.add_argument("--chunk", type=int, default=16)
    b.add_argument("--microbatch", type=int, default=1)
    b.add_argument("--wire", default="buffer", choices=["buffer", "int8"])
    b.add_argument("--seconds", type=float, default=5.0)
    _add_obs_flags(b)

    e = sub.add_parser("export", help="write per-stage StableHLO artifacts")
    e.add_argument("--model", required=True)
    e.add_argument("--stages", type=int)
    e.add_argument("--cuts")
    e.add_argument("--out", required=True)
    e.add_argument("--batch", type=int, default=1)

    nd = sub.add_parser("node", help="run one standalone stage node")
    nd.add_argument("--artifact", default=None,
                    help="pre-placed stage artifact; omit to boot empty "
                         "and receive it in-band (control handshake)")
    nd.add_argument("--listen", required=True, metavar="[host]:port")
    nd.add_argument("--next", default=None, metavar="host:port",
                    help="successor hop (last node: the dispatcher's "
                         "result port); omit to receive it in-band")
    nd.add_argument("--codec", default="raw",
                    help="hop codec: raw | lzb | bf8/bf12/bf16 | "
                         "sleep<ms>+<codec> (bench-only delay wrapper; "
                         "esleep/dsleep delay one side only)")
    nd.add_argument("--connect-timeout", type=float, default=30.0)
    nd.add_argument("--fan-in", type=int, default=1, metavar="R",
                    help="merge R sequence-stamped upstream connections "
                         "(this node sits downstream of a replicated "
                         "stage) through a bounded reorder buffer")
    nd.add_argument("--replica", type=int, default=None, metavar="N",
                    help="this process is replica N of its stage "
                         "(labels stageK.rN spans/stats)")
    nd.add_argument("--fan", choices=["rr", "broadcast"], default="rr",
                    help="multi-hop --next distribution: rr round-robins "
                         "across stage replicas; broadcast sends EVERY "
                         "frame to every hop (the fork of a branched "
                         "stage graph, one shared seq stamp per frame)")
    nd.add_argument("--branch", type=int, default=None, metavar="J",
                    help="this node rides branch path J of a fork/join "
                         "region (labels stageK.bJ spans/stats; the "
                         "outbound stream announces path J to the join)")
    nd.add_argument("--join", type=int, default=0, metavar="P",
                    help="this node is the region's JOIN: merge P "
                         "labeled branch paths per sequence through a "
                         "(path, seq) reorder buffer and run the "
                         "multi-input merge program")
    nd.add_argument("--infer-delay-ms", type=float, default=0.0,
                    help="bench-only: sleep this long per frame in the "
                         "compute loop (simulated accelerator time — "
                         "how the DAG smoke expresses branch compute "
                         "on a 1-core host)")
    nd.add_argument("--prom-port", type=int, default=None, metavar="PORT",
                    help="serve this process's metrics registry as a "
                         "Prometheus scrape endpoint on PORT "
                         "(0 = ephemeral, printed to stderr)")
    nd.add_argument("--tier",
                    choices=["auto", "ici", "local", "shm", "tcp"],
                    default="auto",
                    help="outbound transport-tier policy: auto walks "
                         "the tier ladder on the downstream dial — "
                         "ici (same process + same mesh, live "
                         "device-resident jax.Arrays) over local "
                         "(same process, host ndarray by reference) "
                         "over shm (same host, shared-memory ring + "
                         "socket doorbell) over tcp; ici/local/shm "
                         "pin that single rung's offer; tcp is the "
                         "pure-wire escape hatch — never probe, "
                         "refuse inbound offers (docs/TRANSPORT.md)")
    nd.add_argument("--device", type=int, default=None, metavar="J",
                    help="pin this node's stage program to jax device "
                         "J (jax.devices()[J]): outputs stay resident "
                         "there, and an upstream ici hop device_puts "
                         "each activation onto it — force a multi-"
                         "device host mesh with XLA_FLAGS="
                         "--xla_force_host_platform_device_count=N")
    nd.add_argument("--tier-accept", choices=["auto", "0", "1"],
                    default="auto",
                    help="grant inbound tier offers (default: auto = "
                         "exactly when --tier is not tcp; a stage "
                         "whose own outbound is tcp may still be the "
                         "colocated-tier TARGET of its upstream)")
    nd.add_argument("--failover", action="store_true",
                    help="arm the seq-replay substrate on this node "
                         "(docs/ROBUSTNESS.md): a fan-out retains sent "
                         "frames until the downstream merge acks them "
                         "and self-heals dead replica channels; a "
                         "replica relays acks upstream; a fan-in "
                         "tolerates upstream death within a grace "
                         "window and dedups replayed frames")
    nd.add_argument("--persist", action="store_true",
                    help="survive stream END: keep serving segments "
                         "until a 'shutdown' control frame arrives "
                         "(the live-replan node mode — a quiesce/"
                         "redeploy/resume cycle reuses this process)")
    nd.add_argument("--co-stage", action="append", default=[],
                    metavar="SPEC",
                    help="host an additional stage node in THIS process "
                         "(repeatable): 'listen=host:port[;artifact=P]"
                         "[;next=host:port][;codec=C][;tier=T]"
                         "[;accept=0|1][;device=J]' — hops between "
                         "housemates negotiate the in-process tiers "
                         "(ici when both sides share the mesh, local "
                         "otherwise; accept gates inbound offers, "
                         "default: tier != tcp; device pins the "
                         "housemate's program to jax device J)")
    nd.add_argument("--journal-dir", default="", metavar="DIR",
                    help="black-box flight recorder: spill this "
                         "process's events, obs-row snapshots, and "
                         "sampled spans to a crash-safe on-disk journal "
                         "under DIR (segment ring, per-record CRC, "
                         "clock anchors) readable by `defer_tpu "
                         "postmortem DIR` after any death "
                         "(docs/OBSERVABILITY.md)")
    _add_overlap_flags(nd)

    c = sub.add_parser("chain", help="spawn a local N-process chain and "
                                     "verify vs the single program")
    c.add_argument("--model", default="resnet_tiny")
    c.add_argument("--stages", type=int, default=3)
    c.add_argument("--cuts")
    c.add_argument("--balance", choices=["flops", "bottleneck"],
                   default="flops",
                   help="auto-cut objective for --stages (bottleneck: "
                        "the comm-aware exact solver)")
    c.add_argument("--batch", type=int, default=1)
    c.add_argument("--count", type=int, default=8)
    c.add_argument("--codec", default="raw",
                   choices=["raw", "lzb", "bf8", "bf12", "bf16"])
    c.add_argument("--in-band", action="store_true",
                   help="boot nodes empty; ship artifacts over the "
                        "control handshake")
    c.add_argument("--replicas", default="", metavar="stageK=R,...",
                   help="run stage K as R data-parallel replica "
                        "processes (ordered fan-out/fan-in; adjacent "
                        "stages cannot both be replicated)")
    c.add_argument("--failover", action="store_true",
                   help="arm the seq-replay substrate (docs/"
                        "ROBUSTNESS.md): fan-outs retain frames until "
                        "acked and self-heal dead replica channels, a "
                        "supervisor respawns killed replica processes, "
                        "and the stream completes byte-identical — "
                        "requires an interior replicated stage "
                        "(--replicas) and file-based artifacts "
                        "(no --in-band)")
    c.add_argument("--trace-sample", type=int, default=0, metavar="N",
                   help="waterfall sampling: with --trace-out, stamp "
                        "every frame with its stream sequence number "
                        "and record per-frame spans (plus rx/tx queue-"
                        "wait spans) for 1-in-N frames only")
    c.add_argument("--prom-port", type=int, default=None, metavar="PORT",
                   help="serve the dispatcher process's metrics "
                        "registry as a Prometheus scrape endpoint")
    c.add_argument("--tier", choices=["auto", "shm", "tcp"],
                   default="auto",
                   help="transport-tier policy for every hop INCLUDING "
                        "the dispatcher edges: auto negotiates the "
                        "cheapest fabric per hop — ici (same process + "
                        "same mesh, device-resident) over local (same "
                        "process) over shm (same host, shared-memory "
                        "ring) over tcp; shm pins the shared-memory "
                        "offer; tcp is the escape hatch — pure wire "
                        "end to end.  Pin ici/local on STAGE hops with "
                        "--hop-tiers (the dispatcher is its own "
                        "process, so those rungs cannot hold on its "
                        "edges; docs/TRANSPORT.md)")
    c.add_argument("--hop-tiers", default="", metavar="T0,T1,...",
                   help="per-inter-stage-hop tier list (len = stages-1, "
                        "each tcp|auto|local|shm|ici|device): device "
                        "FUSES the two stages into one jit program, "
                        "ici COLOCATES them in one OS process and "
                        "hands LIVE device-resident jax.Arrays across "
                        "the hop (cross-device via one device_put), "
                        "local colocates with a host-ndarray channel, "
                        "shm keeps separate processes but hands "
                        "activations through a shared-memory ring")
    c.add_argument("--devices", type=int, default=None, metavar="N",
                   help="force an N-device host mesh in every stage "
                        "process (XLA_FLAGS "
                        "--xla_force_host_platform_device_count=N) so "
                        "--device-map can pin stages to distinct "
                        "devices")
    c.add_argument("--device-map", default="", metavar="stageK=J,...",
                   help="pin stage K's program to jax device J — with "
                        "ici hops the upstream device_puts each "
                        "activation device-to-device, never via host")
    c.add_argument("--dag", action="store_true",
                   help="deploy the DAG planner's branch-parallel stage "
                        "GRAPH instead of a linear chain: parallel "
                        "branches run as concurrent processes between a "
                        "broadcast fork and an all-paths join "
                        "(--nodes sets the process budget; replicas / "
                        "hop tiers do not compose with branch fans)")
    c.add_argument("--nodes", type=int, default=0, metavar="N",
                   help="--dag process budget (default: --stages)")
    c.add_argument("--topology", default=None, metavar="FILE",
                   help="deploy an explicit topology JSON (a `plan "
                        "--dag --json` document) instead of solving")
    c.add_argument("--emit-calibration", default="", metavar="FILE",
                   help="after the run, fit CalibratedConstants "
                        "(host_sync/ici/wire bandwidths, per-deployed-"
                        "codec throughputs) from the chain's own "
                        "telemetry and write the versioned JSON "
                        "artifact — feed it back via `plan "
                        "--calibrated` (docs/PLANNER.md)")
    c.add_argument("--journal-dir", default="", metavar="DIR",
                   help="black-box flight recorder: every stage "
                        "process AND the dispatcher journal their "
                        "telemetry under DIR; a failover respawn or "
                        "chain failure auto-emits a postmortem bundle "
                        "with a first-fault verdict, and `defer_tpu "
                        "postmortem DIR` does it on demand")
    _add_overlap_flags(c)
    _add_obs_flags(c)

    sv = sub.add_parser("serve", help="multi-tenant serving front door: "
                                      "admission + continuous batching "
                                      "+ SLO shedding over one chain "
                                      "(docs/SERVING.md)")
    sv.add_argument("--model", default="resnet_tiny")
    sv.add_argument("--stages", type=int, default=3)
    sv.add_argument("--cuts")
    sv.add_argument("--workload", choices=["tensor", "decode"],
                    default="tensor",
                    help="tensor: samples through the deployed chain; "
                         "decode: continuous-batching autoregressive "
                         "generation (gpt* models, prompts in / token "
                         "ids out)")
    sv.add_argument("--listen", default="127.0.0.1:0",
                    metavar="[host]:port")
    sv.add_argument("--nodes", default="", metavar="host:port,...",
                    help="deploy onto these already-running stage nodes "
                         "(one per stage); default: thread-per-stage "
                         "nodes inside this process")
    sv.add_argument("--width", type=int, default=0, metavar="W",
                    help="microbatch width (slots per frame); 0 = from "
                         "--budget-ms, else 4")
    sv.add_argument("--budget-ms", type=float, default=0.0,
                    help="per-stage latency budget: width becomes the "
                         "largest batch whose slowest stage stays "
                         "inside it (plan.max_batch_within_budget)")
    sv.add_argument("--max-width", type=int, default=64)
    sv.add_argument("--batch", type=int, default=1,
                    help="cost-model batch for --budget-ms sizing")
    sv.add_argument("--window", type=int, default=8,
                    help="formed frames in flight inside the chain")
    sv.add_argument("--gather-ms", type=float, default=0.0,
                    help="how long a partial frame waits for company "
                         "(0 = never: the pipeline is the batching "
                         "window)")
    sv.add_argument("--codec", default="raw")
    sv.add_argument("--hop-codecs", default="", metavar="C0,C1,...",
                    help="per-stage outbound hop codecs for the "
                         "deployed chain")
    sv.add_argument("--tenant", action="append", default=[],
                    metavar="NAME=W[:PRI[:DEADLINE_MS]]",
                    help="pre-configure a tenant (repeatable): WFQ "
                         "weight, strict priority, per-sample SLO")
    sv.add_argument("--max-new", type=int, default=16,
                    help="decode mode: default tokens per request")
    sv.add_argument("--seconds", type=float, default=0.0,
                    help="serve for N seconds then exit (0 = forever)")
    sv.add_argument("--prom-port", type=int, default=None, metavar="PORT",
                    help="serve this process's metrics registry — front-"
                         "door admission/shed/completion counters and "
                         "per-tenant histograms included — as a "
                         "Prometheus scrape endpoint on PORT")
    sv.add_argument("--trace-sample", type=int, default=0, metavar="N",
                    help="with --trace-out: request-scoped waterfall "
                         "sampling — 1-in-N formed frames (and every "
                         "request riding them) record spans end to end "
                         "across the front door and every stage "
                         "process, on one clock-aligned timeline "
                         "(docs/OBSERVABILITY.md)")
    sv.add_argument("--journal-dir", default="", metavar="DIR",
                    help="black-box flight recorder: journal the front "
                         "door's events and pressure snapshots under "
                         "DIR; a failed healthcheck auto-emits a "
                         "postmortem bundle (docs/OBSERVABILITY.md)")
    _add_obs_flags(sv)
    _add_cost_flags(sv)

    sc = sub.add_parser("serve-client", help="open-loop load generator "
                                             "against a serve front "
                                             "door (Poisson + bursts)")
    sc.add_argument("--connect", required=True, metavar="host:port")
    sc.add_argument("--tenant", default="default")
    sc.add_argument("--weight", type=float, default=1.0)
    sc.add_argument("--priority", type=int, default=0)
    sc.add_argument("--deadline-ms", type=float, default=0.0,
                    help="per-sample SLO carried in the hello (0 = "
                         "no deadline)")
    sc.add_argument("--rate", type=float, default=20.0,
                    help="mean arrival rate (Hz)")
    sc.add_argument("--seconds", type=float, default=5.0)
    sc.add_argument("--seed", type=int, default=0)
    sc.add_argument("--burst", action="append", default=[],
                    metavar="T0:T1:MULT",
                    help="burst phase: MULTx the base rate over "
                         "[T0, T1) seconds (repeatable)")
    sc.add_argument("--sample-shape", default="32,32,3",
                    help="tensor mode: one sample's shape")
    sc.add_argument("--prompt-len", type=int, default=0,
                    help="decode mode: send random prompts of this "
                         "length instead of tensors")
    sc.add_argument("--vocab", type=int, default=97)
    sc.add_argument("--max-new", type=int, default=0,
                    help="decode mode: tokens per request (rides the "
                         "hello)")

    mo = sub.add_parser("monitor", help="live top-style view of a "
                                        "running chain's obs_push "
                                        "telemetry")
    mo.add_argument("--nodes", default="", metavar="host:port,...",
                    help="the chain nodes' listen addresses (same list "
                         "`stats`/deploy use)")
    mo.add_argument("--interval-ms", type=float, default=500.0,
                    help="push + refresh cadence (each node reports at "
                         "this interval)")
    mo.add_argument("--iterations", type=int, default=0, metavar="N",
                    help="refresh N times then exit (0 = run until ^C)")
    mo.add_argument("--json", action="store_true",
                    help="one JSON line per refresh (rows, bottleneck, "
                         "stragglers) instead of the table")
    mo.add_argument("--plan", metavar="PLAN_JSON",
                    help="a `plan --json` file: enables the straggler "
                         "detector against the plan's per-stage "
                         "expectations")
    mo.add_argument("--model", default=None,
                    help="with --plan: rebuild the layer graph so a "
                         "flagged straggler emits a replan suggestion")
    mo.add_argument("--factor", type=float, default=1.5,
                    help="straggler threshold: live service estimate > "
                         "factor x planned, sustained")
    mo.add_argument("--sustain", type=int, default=2,
                    help="reporting intervals a deviation must hold "
                         "before it is flagged")
    mo.add_argument("--calibrated", default="", metavar="FILE",
                    help="with --plan and --model: overlay a "
                         "CalibratedConstants artifact (`chain "
                         "--emit-calibration`) on the plan's cost "
                         "model before computing the drift auditor's "
                         "per-stage predictions")
    mo.add_argument("--drift-threshold", type=float, default=0.25,
                    help="with --plan and --model: |measured - "
                         "predicted| / predicted past this, sustained "
                         "--sustain intervals, flags the stage and "
                         "emits a model_drift event")
    mo.add_argument("--serve", default="", metavar="host:port",
                    help="also poll a serve front door's stats endpoint "
                         "and render per-tenant columns (admitted / "
                         "shed / queue-delay percentiles / SLO "
                         "attainment / attribution buckets)")
    mo.add_argument("--events", action="store_true",
                    help="render the merged flight-recorder event log "
                         "(sheds, tier negotiations/fallbacks, "
                         "straggler flags, replan suggestions, node "
                         "deaths, stream/client lifecycle) from every "
                         "watched node's obs_push stream and — with "
                         "--serve — the front door's events_since "
                         "endpoint (docs/OBSERVABILITY.md)")
    mo.add_argument("--kind", default="", metavar="a,b",
                    help="with --events/--follow: only render events of "
                         "the listed kinds (comma-separated; e.g. "
                         "recompile,mem_pressure,failover)")
    mo.add_argument("--follow", action="store_true",
                    help="event tail mode (implies --events): one line "
                         "per merged flight-recorder event as it "
                         "arrives, no table — watch a fleet-wide "
                         "recompile/failover storm live")
    mo.add_argument("--align", action="store_true",
                    help="actively clock-ALIGN every node's tracer to "
                         "this process (default: passively estimate "
                         "offsets only — an observer must not re-anchor "
                         "spans the dispatcher already aligned)")
    mo.add_argument("--connect-timeout", type=float, default=30.0)

    pm = sub.add_parser("postmortem",
                        help="assemble a forensics bundle (merged "
                             "timeline, Perfetto trace, last-known "
                             "rows, first-fault verdict) from the "
                             "black-box journals under a --journal-dir "
                             "— works on dead processes")
    pm.add_argument("dir", metavar="JOURNAL_DIR",
                    help="the --journal-dir a node/chain/serve wrote")
    pm.add_argument("--out", default="", metavar="DIR",
                    help="bundle output directory (default: a "
                         "bundle-<stamp> dir inside JOURNAL_DIR)")
    pm.add_argument("--last-s", type=float, default=30.0,
                    help="Perfetto window: keep the final N seconds "
                         "of spans/events in trace.json")
    pm.add_argument("--reason", default="manual",
                    help="reason recorded in the bundle")

    pr = sub.add_parser("profile", help="attach to a running chain for "
                                        "N seconds: per-stage phase "
                                        "breakdown (dispatch/device/"
                                        "host_sync), recompile + "
                                        "memory telemetry, optional "
                                        "merged Perfetto trace")
    pr.add_argument("--nodes", required=True, metavar="host:port,...",
                    help="the chain nodes' listen addresses (same list "
                         "`stats`/monitor use)")
    pr.add_argument("--seconds", type=float, default=5.0,
                    help="profiled window length")
    pr.add_argument("--out", default="", metavar="FILE",
                    help="write the per-stage phase-breakdown JSON "
                         "here (default: one JSON line on stdout)")
    pr.add_argument("--spans", action="store_true",
                    help="also collect each node's spans (trace + "
                         "trace_dump) onto one clock-aligned timeline")
    pr.add_argument("--trace-out", default="", metavar="FILE",
                    help="export the merged timeline as Chrome/"
                         "Perfetto trace JSON (implies --spans)")
    pr.add_argument("--sample-every", type=int, default=0,
                    help="span sampling: record every Nth wire "
                         "sequence (0 = every frame — the window is "
                         "short)")
    pr.add_argument("--jax-trace-dir", default="", metavar="DIR",
                    help="ask each node to wrap the window in "
                         "jax.profiler.trace writing under DIR/<addr> "
                         "(backends with a profiler; no-op on cpu)")
    pr.add_argument("--connect-timeout", type=float, default=30.0)

    t = sub.add_parser("train", help="pipeline-parallel training demo "
                                     "(synthetic data, cross-entropy)")
    t.add_argument("--model", default="resnet_tiny")
    t.add_argument("--stages", type=int, default=4)
    t.add_argument("--cuts")
    t.add_argument("--chunk", type=int, default=8)
    t.add_argument("--microbatch", type=int, default=1)
    t.add_argument("--steps", type=int, default=5)
    t.add_argument("--lr", type=float, default=1e-3)
    t.add_argument("--wire", default="buffer", choices=["buffer", "int8"],
                   help="int8: train the quantized deployment (STE)")
    t.add_argument("--save", help="write a training checkpoint here")

    g = sub.add_parser("generate", help="pipelined autoregressive "
                                        "generation demo (gpt models)")
    g.add_argument("--model", default="gpt_tiny")
    g.add_argument("--stages", type=int, default=4)
    g.add_argument("--microbatch", type=int, default=2)
    g.add_argument("--prompt-len", type=int, default=4)
    g.add_argument("--new-tokens", type=int, default=8)
    g.add_argument("--temperature", type=float, default=0.0)
    g.add_argument("--top-k", type=int, default=None)
    g.add_argument("--seed", type=int, default=0)
    g.add_argument("--prefill", action="store_true",
                   help="fused full-sequence prompt prefill")
    g.add_argument("--token-chunk", type=int, default=None)
    g.add_argument("--kv-cache", default="buffer",
                   choices=["buffer", "int8"],
                   help="int8: quantized KV cache (~1 byte/value reads)")
    g.add_argument("--weight-dtype", default="",
                   choices=["", "int8"],
                   help="int8: W8A16 weight-only quantization "
                        "(channel-wise scales, dequant fused per stage)")
    g.add_argument("--beam", type=int, default=1,
                   help="beam width (must divide --microbatch)")
    _add_obs_flags(g)

    args = ap.parse_args(argv)
    {"models": cmd_models, "partition": cmd_partition, "plan": cmd_plan,
     "bench": cmd_bench, "export": cmd_export, "node": cmd_node,
     "chain": cmd_chain, "monitor": cmd_monitor, "train": cmd_train,
     "generate": cmd_generate, "serve": cmd_serve,
     "serve-client": cmd_serve_client,
     "postmortem": cmd_postmortem,
     "profile": cmd_profile}[args.cmd](args)


if __name__ == "__main__":
    main()
