"""Host-side codecs for the host/DCN edge of the pipeline.

Capability parity with the reference's wire codec — ``lz4(zfp(array))`` on
every payload (reference src/dispatcher.py:81-82, src/node.py:76-77) — built
TPU-first:

  * On-pod stage→stage transfers use NO codec: activations stay in HBM and
    ride ICI (SURVEY.md §2.2).  The in-pipeline "compression" analogue is the
    bfloat16 transfer buffer (``SpmdPipeline(buffer_dtype=bfloat16)``).
  * The host/DCN edge (streaming ingest/egress, weight shipping to remote
    hosts) uses first-party native codecs from ``_native/codec.cpp``:
    ``blockfloat`` (fixed-rate shared-exponent float codec, a ZFP-fixed-rate
    analogue) + ``lzb`` (LZ77 byte compressor, an LZ4 analogue), composed the
    same way the reference composes ZFP then LZ4.

The C++ library is compiled on demand with g++; if no toolchain is available
a pure-NumPy fallback implements the identical formats, so the Python API
never changes behavior — only speed.
"""

from .codecs import (BlockFloatCodec, Codec, LosslessCodec, PipelineCodec,
                     RawCodec, native_available)

__all__ = ["Codec", "BlockFloatCodec", "LosslessCodec", "PipelineCodec",
           "RawCodec", "native_available"]
