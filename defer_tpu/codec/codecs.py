"""Codec implementations: native-backed with NumPy fallbacks.

Formats are defined by ``_native/codec.cpp`` (blockfloat ``BFC1`` and lzb
``LZB1``); the NumPy paths implement the identical wire formats so payloads
are interchangeable between backends.
"""

from __future__ import annotations

import ctypes

import numpy as np

from . import native

BF_BLOCK = 64


def native_available() -> bool:
    return native.load() is not None


class Codec:
    """encode(array) -> (payload bytes, metadata); decode inverts it.

    The role ``_comp``/``_decomp`` play in the reference
    (src/dispatcher.py:81-84, src/node.py:76-79), as an explicit interface.
    """

    name = "codec"

    def encode(self, arr: np.ndarray) -> bytes:
        raise NotImplementedError

    def decode(self, data: bytes, shape, dtype) -> np.ndarray:
        raise NotImplementedError


class RawCodec(Codec):
    """Identity codec (the ICI path: no host-side compression at all)."""

    name = "raw"

    def encode(self, arr):
        return np.ascontiguousarray(arr).tobytes()

    def decode(self, data, shape, dtype):
        return np.frombuffer(data, dtype=dtype).reshape(shape).copy()


# ---------------------------------------------------------------------------
# blockfloat
# ---------------------------------------------------------------------------


def _bf_compress_np(x: np.ndarray, bits: int) -> bytes:
    """NumPy implementation of the BFC1 format (see codec.cpp)."""
    n = x.size
    if n == 0:
        return b"BFC1" + (0).to_bytes(8, "little") + bytes([bits, 0, 0, 0])
    flat = np.ascontiguousarray(x, np.float32).ravel()
    flat = np.where(np.isfinite(flat), flat, 0.0).astype(np.float32)
    nblocks = (n + BF_BLOCK - 1) // BF_BLOCK
    padded = np.zeros(nblocks * BF_BLOCK, np.float32)
    padded[:n] = flat
    blocks = padded.reshape(nblocks, BF_BLOCK)

    amax = np.abs(blocks).max(axis=1)
    # frexp: amax = m * 2^e with m in [0.5, 1); e = 0 where amax == 0
    _, e = np.frexp(amax)
    # clamp so the biased exponent byte can't wrap (mirrors codec.cpp)
    e = np.clip(e, -127, 127)
    qmax = (1 << (bits - 1)) - 1
    # float64: 2^127 * qmax overflows float32 (mirrors codec.cpp)
    scale = np.ldexp(np.float64(1.0), -e) * qmax
    v = blocks.astype(np.float64) * scale[:, None]
    # lround semantics: round half away from zero
    q = np.sign(v) * np.floor(np.abs(v) + 0.5)
    q = np.clip(q, -qmax, qmax).astype(np.int64)
    u = (q + qmax).astype(np.uint32)

    # LSB-first bit stream per block, packed to bytes
    bit_idx = np.arange(bits, dtype=np.uint32)
    ubits = ((u[:, :, None] >> bit_idx[None, None, :]) & 1).astype(np.uint8)
    payload = np.packbits(ubits.reshape(nblocks, -1), axis=1,
                          bitorder="little")

    header = b"BFC1" + int(n).to_bytes(8, "little") + bytes([bits, 0, 0, 0])
    if nblocks:
        body = np.concatenate(
            [(e + 128).astype(np.uint8)[:, None], payload], axis=1).ravel()
    else:
        body = np.zeros(0, np.uint8)
    return header + body.tobytes()


def _bf_decompress_np(data: bytes) -> np.ndarray:
    if len(data) < 16 or data[:4] != b"BFC1":
        raise ValueError("not a BFC1 payload")
    n = int.from_bytes(data[4:12], "little")
    bits = data[12]
    qmax = (1 << (bits - 1)) - 1
    nblocks = (n + BF_BLOCK - 1) // BF_BLOCK
    payload_len = (BF_BLOCK * bits + 7) // 8
    body = np.frombuffer(data, np.uint8, offset=16).reshape(
        nblocks, 1 + payload_len)
    e = body[:, 0].astype(np.int64) - 128
    bits_arr = np.unpackbits(body[:, 1:], axis=1, bitorder="little")
    bits_arr = bits_arr[:, : BF_BLOCK * bits].reshape(nblocks, BF_BLOCK, bits)
    u = (bits_arr.astype(np.uint32)
         << np.arange(bits, dtype=np.uint32)[None, None, :]).sum(axis=2)
    q = u.astype(np.int64) - qmax
    inv = np.ldexp(np.float64(1.0), e) / qmax
    out = (q * inv[:, None]).astype(np.float32).ravel()
    return out[:n]


class BlockFloatCodec(Codec):
    """Fixed-rate lossy float codec (ZFP-fixed-rate analogue).

    ``bits`` mantissa bits per value + 1 shared exponent byte per 64 values:
    rate = bits/value + 0.125, relative error <= 2^-(bits-1) of the block
    max.  bits=8 roughly matches bf16 mantissa fidelity at half the size of
    f32.
    """

    name = "blockfloat"

    def __init__(self, bits: int = 8, force_numpy: bool = False):
        if not 2 <= bits <= 24:
            raise ValueError("bits must be in [2, 24]")
        self.bits = bits
        self._lib = None if force_numpy else native.load()

    def encode(self, arr):
        x = np.ascontiguousarray(arr, np.float32)
        if self._lib is None:
            return _bf_compress_np(x, self.bits)
        lib = self._lib
        cap = lib.bf_max_compressed_size(x.size, self.bits)
        out = np.empty(cap, np.uint8)
        written = lib.bf_compress(
            x.ravel().ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            x.size, self.bits,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
        if written < 0:
            raise ValueError("bf_compress failed")
        return out[:written].tobytes()

    def decode(self, data, shape, dtype=np.float32):
        expected = int(np.prod(shape, dtype=np.int64))
        if self._lib is None:
            flat = _bf_decompress_np(data)
            if flat.size != expected:
                raise ValueError(
                    f"BFC1 payload declares {flat.size} values, "
                    f"expected {expected}")
        else:
            lib = self._lib
            buf = np.frombuffer(data, np.uint8)
            n = lib.bf_peek_count(
                buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), buf.size)
            if n < 0:
                raise ValueError("not a BFC1 payload")
            if n != expected:
                # validate the header count against the caller's shape BEFORE
                # allocating: a corrupt/hostile 20-byte payload could other-
                # wise declare a multi-terabyte output
                raise ValueError(
                    f"BFC1 payload declares {n} values, expected {expected}")
            flat = np.empty(n, np.float32)
            got = lib.bf_decompress(
                buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), buf.size,
                flat.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
            if got != n:
                raise ValueError("bf_decompress failed")
        return flat.reshape(shape).astype(dtype)


# ---------------------------------------------------------------------------
# lzb (LZ77) — byte-level, layered over blockfloat by PipelineCodec
# ---------------------------------------------------------------------------

_LZB_MIN_MATCH = 4


def _put_varint(v: int) -> bytes:
    out = bytearray()
    while v >= 0x80:
        out.append((v & 0x7F) | 0x80)
        v >>= 7
    out.append(v)
    return bytes(out)


def _get_varint(data: bytes, i: int) -> tuple[int, int]:
    r, shift = 0, 0
    while True:
        b = data[i]
        i += 1
        r |= (b & 0x7F) << shift
        if not b & 0x80:
            return r, i
        shift += 7
        if shift > 63:
            raise ValueError("varint overflow")


def _lzb_compress_py(src: bytes) -> bytes:
    """Python mirror of lzb_compress (greedy hash-head matcher)."""
    n = len(src)
    out = bytearray(b"LZB1")
    out += _put_varint(n)
    head: dict[int, int] = {}
    i = lit_start = 0

    def flush(upto: int):
        nonlocal lit_start
        while lit_start < upto:
            take = min(upto - lit_start, 128)
            out.append(take - 1)
            out.extend(src[lit_start:lit_start + take])
            lit_start += take

    while i + _LZB_MIN_MATCH <= n:
        # the SAME 16-bit multiplicative hash as the native matcher
        # (codec.cpp lzb_hash), so both backends pick identical match
        # candidates — including collisions — and emit identical streams
        v = int.from_bytes(src[i:i + 4], "little")
        key = ((v * 2654435761) & 0xFFFFFFFF) >> 16
        cand = head.get(key, -1)
        head[key] = i
        if cand >= 0 and i - cand <= 0xFFFF \
                and src[cand:cand + 4] == src[i:i + 4]:
            length = _LZB_MIN_MATCH
            maxlen = min(n - i, 127 + _LZB_MIN_MATCH)
            while length < maxlen and src[cand + length] == src[i + length]:
                length += 1
            flush(i)
            out.append(0x80 | (length - _LZB_MIN_MATCH))
            out += _put_varint(i - cand)
            i += length
            lit_start = i
        else:
            i += 1
    flush(n)
    return bytes(out)


def _lzb_decompress_py(data: bytes) -> bytes:
    if len(data) < 5 or data[:4] != b"LZB1":
        raise ValueError("not an LZB1 payload")
    n, i = _get_varint(data, 4)
    out = bytearray()
    while len(out) < n:
        c = data[i]
        i += 1
        if c & 0x80:
            length = (c & 0x7F) + _LZB_MIN_MATCH
            dist, i = _get_varint(data, i)
            if dist == 0 or dist > len(out):
                raise ValueError("corrupt match")
            for _ in range(length):  # overlap-safe byte-by-byte
                out.append(out[-dist])
        else:
            length = c + 1
            out += data[i:i + length]
            i += length
    if len(out) != n:
        raise ValueError("corrupt stream")
    return bytes(out)


def _lzb_compress(data: bytes, lib) -> bytes:
    if lib is None:
        return _lzb_compress_py(data)
    src = np.frombuffer(data, np.uint8)
    cap = lib.lzb_max_compressed_size(src.size)
    out = np.empty(cap, np.uint8)
    written = lib.lzb_compress(
        src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), src.size,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
    if written < 0:
        raise ValueError("lzb_compress failed")
    if written > cap:  # the bound is the memory-safety contract: a
        # breach means the heap is already overrun — fail IMMEDIATELY
        # and loudly instead of aborting at some later malloc (the r5
        # 12.8 MB activation-payload failure mode)
        raise RuntimeError(
            f"lzb_compress wrote {written} > capacity {cap}: "
            f"lzb_max_compressed_size bound violated")
    return out[:written].tobytes()


def _lzb_decompress(data: bytes, lib, expected: int | None = None) -> bytes:
    if lib is None:
        if expected is not None:
            # validate the declared size BEFORE decompressing — a hostile
            # ~30-byte header must not drive an unbounded output loop
            if len(data) < 5 or data[:4] != b"LZB1":
                raise ValueError("not an LZB1 payload")
            n, _ = _get_varint(data, 4)
            if n != expected:
                raise ValueError(
                    f"LZB1 payload declares {n} bytes, expected {expected}")
        out = _lzb_decompress_py(data)
        if expected is not None and len(out) != expected:
            raise ValueError(
                f"LZB1 payload is {len(out)} bytes, expected {expected}")
        return out
    src = np.frombuffer(data, np.uint8)
    n = lib.lzb_decompressed_size(
        src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), src.size)
    if n < 0:
        raise ValueError("not an LZB1 payload")
    if expected is not None and n != expected:
        # bound the allocation by what the caller expects — a hostile header
        # must not pick the output size
        raise ValueError(
            f"LZB1 payload declares {n} bytes, expected {expected}")
    out = np.empty(n, np.uint8)
    got = lib.lzb_decompress(
        src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), src.size,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), n)
    if got != n:
        raise ValueError("lzb_decompress failed")
    return out.tobytes()


class PipelineCodec(Codec):
    """blockfloat + lzb composition — the reference's ``lz4(zfp(arr))``
    stack (src/dispatcher.py:82) as one symmetric codec (the reference's
    decode sides are asymmetric/buggy; see SURVEY.md §3.5)."""

    name = "blockfloat+lzb"

    def __init__(self, bits: int = 8, force_numpy: bool = False):
        self._bf = BlockFloatCodec(bits, force_numpy)
        self._lib = None if force_numpy else native.load()

    def encode(self, arr):
        return _lzb_compress(self._bf.encode(arr), self._lib)

    def decode(self, data, shape, dtype=np.float32):
        n = int(np.prod(shape, dtype=np.int64))
        nblocks = (n + BF_BLOCK - 1) // BF_BLOCK
        expected = 16 + nblocks * (1 + (BF_BLOCK * self._bf.bits + 7) // 8)
        return self._bf.decode(
            _lzb_decompress(data, self._lib, expected=expected), shape, dtype)


class LosslessCodec(Codec):
    """lzb over raw bytes: lossless path for weights/ints (any dtype)."""

    name = "lzb"

    def __init__(self, force_numpy: bool = False):
        self._lib = None if force_numpy else native.load()

    def encode(self, arr):
        return _lzb_compress(np.ascontiguousarray(arr).tobytes(), self._lib)

    def decode(self, data, shape, dtype):
        expected = int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize
        raw = _lzb_decompress(data, self._lib, expected=expected)
        return np.frombuffer(raw, dtype=dtype).reshape(shape).copy()
