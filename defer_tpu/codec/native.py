"""ctypes loader for the first-party native codec library.

Compiles ``_native/codec.cpp`` with g++ on first use (no pip deps, no
pybind11 — plain C ABI via ctypes).  Returns None if no toolchain is
available; callers fall back to the NumPy implementation of the identical
wire formats.
"""

from __future__ import annotations

import ctypes
import os
import threading

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "_native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libdefercodec.so")

_lock = threading.Lock()
_lib = None
_tried = False


def load():
    """The loaded ctypes library, or None if unavailable.

    A rebuild-needing (missing OR stale) library that fails to build
    yields None — the NumPy fallback — never the stale binary."""
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        from ..utils._nativebuild import ensure_built
        if not ensure_built(os.path.join(_NATIVE_DIR, "codec.cpp"),
                            _SO_PATH):
            return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError:
            return None
        c_i64, c_int = ctypes.c_int64, ctypes.c_int
        u8p = ctypes.POINTER(ctypes.c_uint8)
        f32p = ctypes.POINTER(ctypes.c_float)
        lib.bf_max_compressed_size.restype = c_i64
        lib.bf_max_compressed_size.argtypes = [c_i64, c_int]
        lib.bf_compress.restype = c_i64
        lib.bf_compress.argtypes = [f32p, c_i64, c_int, u8p]
        lib.bf_decompress.restype = c_i64
        lib.bf_decompress.argtypes = [u8p, c_i64, f32p]
        lib.bf_peek_count.restype = c_i64
        lib.bf_peek_count.argtypes = [u8p, c_i64]
        lib.lzb_max_compressed_size.restype = c_i64
        lib.lzb_max_compressed_size.argtypes = [c_i64]
        lib.lzb_compress.restype = c_i64
        lib.lzb_compress.argtypes = [u8p, c_i64, u8p]
        lib.lzb_decompressed_size.restype = c_i64
        lib.lzb_decompressed_size.argtypes = [u8p, c_i64]
        lib.lzb_decompress.restype = c_i64
        lib.lzb_decompress.argtypes = [u8p, c_i64, u8p, c_i64]
        _lib = lib
        return _lib
