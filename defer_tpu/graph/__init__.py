from .ir import GraphBuilder, LayerGraph, LayerNode, Op, ShapeSpec
from .analysis import (auto_cut_points, max_activation_bytes,
                       max_activation_elems, node_flops, total_flops,
                       valid_cut_points)
from .viz import summary, to_dot
from . import ops
