"""Graph analysis: cut-point discovery, cost modeling, auto-partitioning.

The reference requires partition boundaries to be single-tensor cut points but
never checks this — it silently relies on the caller cutting ResNet50 only at
``add_*`` articulation layers (reference test/test.py:18; the single Input at
src/dag_util.py:28 is the implicit constraint).  Here cut validity is computed
from the DAG: a node ``v`` is a valid cut iff *every* edge from the prefix
(nodes up to and including ``v`` in topological order) into the suffix
originates at ``v`` — i.e. exactly one tensor crosses the boundary.  Invalid
cuts fail loudly in the partitioner (SURVEY.md §7 hard part 3).
"""

from __future__ import annotations

import dataclasses

from .ir import LayerGraph


def valid_cut_points(graph: LayerGraph) -> list[str]:
    """Names of nodes whose output is the *only* tensor crossing the cut.

    Linear scan over the topological order: a cut after position ``i`` is
    valid iff no node earlier than ``i`` has a consumer later than ``i``.
    The graph output itself is excluded (cutting there yields an empty
    stage).
    """
    order = graph.topo_order
    pos = {name: i for i, name in enumerate(order)}
    pos[graph.input_name] = -1

    # Latest consumer position for every tensor (input + all nodes).
    last_use = {graph.input_name: -1}
    for name in order:
        last_use.setdefault(name, pos[name])
        for src in graph.nodes[name].inputs:
            last_use[src] = max(last_use[src], pos[name])

    cuts = []
    running_max = last_use[graph.input_name]
    for i, name in enumerate(order):
        if i > 0:
            running_max = max(running_max, last_use[order[i - 1]])
        # Edges from strictly-earlier nodes may not reach past position i.
        if running_max <= i and name != graph.output_name:
            cuts.append(name)
    return cuts


# -- branch structure (DAG-shaped pipelines, docs/PLANNER.md) ---------------
#
# A linear cut can only split a branching model at its articulation
# points, so everything BETWEEN two articulations — the parallel
# branches of an inception block, the experts of a branched MoE layer —
# is an indivisible block to the chain runtime.  The structures below
# expose exactly that block structure: which articulation-to-
# articulation regions decompose into disjoint parallel branches, so the
# DAG planner (``plan/dag.py``) can place each branch on its own node(s)
# and the branched runtime (``runtime/topology.py``) can mirror the
# graph's shape instead of serializing it.


@dataclasses.dataclass(frozen=True)
class Branch:
    """One parallel branch of a :class:`BranchRegion`: a single-input
    (the region's fork tensor) single-output sub-DAG.  ``nodes`` is
    empty for a direct fork->join edge (a residual skip): the fork's
    tensor itself is that path's contribution to the join."""

    nodes: tuple[str, ...]   #: topo order; () = direct fork->join edge
    out: str                 #: the join input this branch feeds

    @property
    def empty(self) -> bool:
        return not self.nodes


@dataclasses.dataclass(frozen=True)
class BranchRegion:
    """A fork/join region of the DAG: every node strictly between the
    articulation point ``fork`` and the merge node ``join`` partitions
    into >= 2 disjoint parallel branches, one per ``join`` input (in the
    join op's input order — that order IS the runtime path order)."""

    fork: str                     #: articulation (or graph input)
    join: str                     #: the merge node (>= 2 inputs)
    branches: tuple[Branch, ...]  #: one per join input, in input order

    @property
    def width(self) -> int:
        return len(self.branches)

    @property
    def branch_nodes(self) -> tuple[str, ...]:
        return tuple(n for b in self.branches for n in b.nodes)


def branch_regions(graph: LayerGraph) -> list[BranchRegion]:
    """The graph's separable fork/join regions, in topological order.

    For every pair of consecutive articulation points ``(a, b)`` (graph
    input and output included) holding more than one node, the block is
    a region iff its final node ``b`` is a merge (>= 2 inputs) and the
    strictly-inner nodes partition into pairwise-disjoint ancestor sets,
    one per merge input (an input equal to ``a`` is an empty branch — a
    residual skip).  Non-separable blocks — a shared intermediate
    feeding two merge inputs, duplicate merge inputs, or a merge that is
    not the block's final node — are simply not regions: they stay
    indivisible to every planner, linear or DAG.
    """
    order = graph.topo_order
    pos = {n: i for i, n in enumerate(order)}
    pos[graph.input_name] = -1
    arts = ([graph.input_name] + valid_cut_points(graph)
            + [graph.output_name])
    regions: list[BranchRegion] = []
    for a, b in zip(arts, arts[1:]):
        block = order[pos[a] + 1: pos[b] + 1]
        if len(block) <= 1:
            continue
        join = block[-1]
        assert join == b
        jn = graph.nodes[join]
        if len(jn.inputs) < 2:
            continue
        inner = set(block[:-1])
        comps: list[tuple[str, ...]] = []
        claimed: set[str] = set()
        ok = True
        for inp in jn.inputs:
            if inp == a:
                if () in comps:
                    ok = False  # fork consumed twice: duplicate input
                    break
                comps.append(())  # residual skip: direct fork->join
                continue
            if inp not in inner:
                ok = False  # duplicate input, or reaches outside
                break
            # ancestor closure of this join input within the block
            comp: set[str] = set()
            stack = [inp]
            while stack:
                n = stack.pop()
                if n in comp:
                    continue
                comp.add(n)
                for p in graph.nodes[n].inputs:
                    if p in inner and p not in comp:
                        stack.append(p)
            if comp & claimed:
                ok = False  # shared intermediate: not separable
                break
            claimed |= comp
            comps.append(tuple(sorted(comp, key=pos.__getitem__)))
        if not ok or claimed != inner:
            continue
        regions.append(BranchRegion(
            fork=a, join=join,
            branches=tuple(Branch(nodes=c, out=c[-1] if c else a)
                           for c in comps)))
    return regions


def segment_cut_points(graph: LayerGraph, nodes, seed: str) -> list[str]:
    """Valid single-tensor cuts WITHIN an ordered node slice.

    ``nodes`` is a topologically ordered slice (a branch body, or a
    trunk segment) whose only external input is ``seed``'s tensor; a
    node ``v`` is a valid internal cut iff no earlier slice node (nor
    ``seed``) has a consumer after ``v`` inside the slice.  The slice's
    final node is excluded (cutting there is the slice's own outbound
    boundary, not an internal cut) — mirroring how
    :func:`valid_cut_points` excludes the graph output.
    """
    nodes = list(nodes)
    if len(nodes) <= 1:
        return []
    pos = {n: i for i, n in enumerate(nodes)}
    last_use = {seed: -1}
    for n in nodes:
        last_use.setdefault(n, pos[n])
        for src in graph.nodes[n].inputs:
            if src in pos or src == seed:
                last_use[src] = max(last_use.get(src, -1), pos[n])
    cuts = []
    running = last_use[seed]
    for i, n in enumerate(nodes[:-1]):
        if i > 0:
            running = max(running, last_use[nodes[i - 1]])
        if running <= i:
            cuts.append(n)
    return cuts


def dag_cut_points(graph: LayerGraph) -> list[str]:
    """Every cut point of the stage *graph*: the linear articulation
    cuts PLUS each separable branch's internal cuts — the namespace
    ``hop_tiers`` keys and DAG plans draw from (a branch-internal hop is
    a real deployable boundary once branches run as their own
    sub-pipelines)."""
    cuts = list(valid_cut_points(graph))
    seen = set(cuts)
    for r in branch_regions(graph):
        for br in r.branches:
            for c in segment_cut_points(graph, br.nodes, r.fork):
                if c not in seen:
                    seen.add(c)
                    cuts.append(c)
    order = {n: i for i, n in enumerate(graph.topo_order)}
    cuts.sort(key=order.__getitem__)
    return cuts


def linear_cut_shortage(graph: LayerGraph, num_stages: int) -> str | None:
    """Pre-validation for the linear planners: ``None`` when
    ``num_stages`` fits the graph's valid linear cuts, else a message
    that names the offending merge nodes — the branch regions whose
    bodies a linear cut cannot split — and points at the DAG planner.
    The CLI raises this instead of letting the request die deep in the
    DP with a bare cut-count error."""
    cuts = valid_cut_points(graph)
    if num_stages <= len(cuts) + 1:
        return None
    msg = (f"graph {graph.name!r} has only {len(cuts)} valid linear cut "
           f"points ({len(cuts) + 1} stages max); cannot make "
           f"{num_stages} stages.")
    regions = branch_regions(graph)
    if regions:
        locked = sum(len(r.branch_nodes) for r in regions)
        joins = [r.join for r in regions]
        shown = ",".join(joins[:6]) + ("..." if len(joins) > 6 else "")
        msg += (f"  {locked} of {len(graph.nodes)} nodes are locked "
                f"inside the parallel branches of {len(regions)} merge "
                f"node(s) [{shown}] — a linear cut cannot split a "
                f"branch body.  Use the DAG planner (`plan --dag`) to "
                f"run branches as concurrent sub-pipelines instead.")
    return msg


def node_flops(graph: LayerGraph, name: str) -> int:
    node = graph.nodes[name]
    in_specs = tuple(graph.out_spec(i) for i in node.inputs)
    return node.op.flops(in_specs, node.out_spec)


def total_flops(graph: LayerGraph) -> int:
    return sum(node_flops(graph, n) for n in graph.topo_order)


def auto_cut_points(graph: LayerGraph, num_stages: int,
                    costs: dict[str, float] | None = None, *,
                    objective: str = "quantile",
                    cost_model=None) -> list[str]:
    """Pick ``num_stages - 1`` valid cuts balancing per-stage cost.

    This is the principled version of DEFER's hand-listed
    ``["add_2", "add_4", ...]`` (reference test/test.py:18): cumulative cost
    quantiles snapped to the nearest valid articulation point.

    ``costs`` maps node name -> per-node cost; default is the analytic
    FLOP model.  Pass measured per-node seconds (e.g. from
    ``utils.profiling.measured_node_costs``) to balance on what the
    hardware actually does — the FLOP model under-weights
    bandwidth-bound ops (pools, norms, cheap convs at high resolution),
    so measured balancing typically moves cuts earlier in CNNs.

    ``objective="bottleneck"`` delegates to the exact comm-aware solver
    (``defer_tpu.plan``): it minimizes ``max_k max(compute_k, comm_k)``
    instead of compute quantiles, which matters whenever a quantile cut
    lands on a fat activation boundary.  ``cost_model`` (a
    ``plan.StageCostModel``) customizes hardware/codec assumptions;
    otherwise an analytic model is built (using ``costs`` as measured
    node seconds when given).  The quantile greedy stays the default —
    it is the measurable baseline ``benchmarks/run.py`` compares against.
    """
    if num_stages < 1:
        raise ValueError("num_stages must be >= 1")
    if objective == "bottleneck":
        from ..plan import StageCostModel, solve
        if cost_model is None:
            cost_model = StageCostModel(graph, node_costs=costs)
        return solve(graph, num_stages, cost_model).cuts
    if objective != "quantile":
        raise ValueError(f"unknown objective {objective!r}; "
                         "use 'quantile' or 'bottleneck'")
    if num_stages == 1:
        return []
    cuts = valid_cut_points(graph)
    if len(cuts) < num_stages - 1:
        raise ValueError(
            f"graph {graph.name!r} has only {len(cuts)} valid cut points; "
            f"cannot make {num_stages} stages")

    order = graph.topo_order
    if costs is not None:
        missing = [n for n in order if n not in costs]
        if missing:
            raise ValueError(f"costs missing nodes: {missing[:5]}...")
    cum = {}
    acc = 0
    for name in order:
        acc += costs[name] if costs is not None else node_flops(graph, name)
        cum[name] = acc
    # guard ONLY exactly-zero totals: max(acc, 1) would clamp sub-1.0
    # measured-seconds sums to 1 and push every quantile target past the
    # end of the curve (collapsing all cuts to the tail)
    total = acc if acc > 0 else 1

    chosen: list[str] = []
    available = list(cuts)
    for j in range(1, num_stages):
        target = total * j / num_stages
        # nearest still-available cut by cumulative cost, keeping order —
        # restricted so enough candidates REMAIN for the later cuts (a
        # greedy pick near the tail could otherwise exhaust the pool;
        # skewed measured-cost maps hit this where the smooth FLOP model
        # rarely did)
        remaining_after = num_stages - 1 - j
        cands = available[: len(available) - remaining_after]
        best = min(cands, key=lambda n: abs(cum[n] - target))
        chosen.append(best)
        # drop this cut and everything before it to preserve ordering
        available = available[available.index(best) + 1:]
    return chosen


def max_activation_elems(graph: LayerGraph, cut_points: list[str]) -> int:
    """Largest per-sample tensor crossing any stage boundary (incl. graph
    input/output) — sizes the SPMD pipeline's homogeneous transfer buffer."""
    sizes = [graph.input_spec.size, graph.output_spec.size]
    sizes += [graph.out_spec(c).size for c in cut_points]
    return max(sizes)


def max_activation_bytes(graph: LayerGraph, cut_points: list[str], *,
                         batch: int = 1) -> int:
    """Largest boundary tensor in BYTES (dtype itemsize included, times
    ``batch``) — what one hop frame of a process chain actually weighs.
    ``max_activation_elems`` undercounts mixed-dtype graphs (an int32
    token boundary and an f32 activation boundary of equal ``size``
    differ on the wire); this is the number that sizes kernel socket
    buffers (``transport.framed.default_sock_buf``) and the planner's
    comm model."""
    specs = [graph.input_spec, graph.output_spec]
    specs += [graph.out_spec(c) for c in cut_points]
    return max(s.size * s.dtype.itemsize for s in specs) * max(batch, 1)
