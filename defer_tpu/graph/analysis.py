"""Graph analysis: cut-point discovery, cost modeling, auto-partitioning.

The reference requires partition boundaries to be single-tensor cut points but
never checks this — it silently relies on the caller cutting ResNet50 only at
``add_*`` articulation layers (reference test/test.py:18; the single Input at
src/dag_util.py:28 is the implicit constraint).  Here cut validity is computed
from the DAG: a node ``v`` is a valid cut iff *every* edge from the prefix
(nodes up to and including ``v`` in topological order) into the suffix
originates at ``v`` — i.e. exactly one tensor crosses the boundary.  Invalid
cuts fail loudly in the partitioner (SURVEY.md §7 hard part 3).
"""

from __future__ import annotations

from .ir import LayerGraph


def valid_cut_points(graph: LayerGraph) -> list[str]:
    """Names of nodes whose output is the *only* tensor crossing the cut.

    Linear scan over the topological order: a cut after position ``i`` is
    valid iff no node earlier than ``i`` has a consumer later than ``i``.
    The graph output itself is excluded (cutting there yields an empty
    stage).
    """
    order = graph.topo_order
    pos = {name: i for i, name in enumerate(order)}
    pos[graph.input_name] = -1

    # Latest consumer position for every tensor (input + all nodes).
    last_use = {graph.input_name: -1}
    for name in order:
        last_use.setdefault(name, pos[name])
        for src in graph.nodes[name].inputs:
            last_use[src] = max(last_use[src], pos[name])

    cuts = []
    running_max = last_use[graph.input_name]
    for i, name in enumerate(order):
        if i > 0:
            running_max = max(running_max, last_use[order[i - 1]])
        # Edges from strictly-earlier nodes may not reach past position i.
        if running_max <= i and name != graph.output_name:
            cuts.append(name)
    return cuts


def node_flops(graph: LayerGraph, name: str) -> int:
    node = graph.nodes[name]
    in_specs = tuple(graph.out_spec(i) for i in node.inputs)
    return node.op.flops(in_specs, node.out_spec)


def total_flops(graph: LayerGraph) -> int:
    return sum(node_flops(graph, n) for n in graph.topo_order)


def auto_cut_points(graph: LayerGraph, num_stages: int,
                    costs: dict[str, float] | None = None, *,
                    objective: str = "quantile",
                    cost_model=None) -> list[str]:
    """Pick ``num_stages - 1`` valid cuts balancing per-stage cost.

    This is the principled version of DEFER's hand-listed
    ``["add_2", "add_4", ...]`` (reference test/test.py:18): cumulative cost
    quantiles snapped to the nearest valid articulation point.

    ``costs`` maps node name -> per-node cost; default is the analytic
    FLOP model.  Pass measured per-node seconds (e.g. from
    ``utils.profiling.measured_node_costs``) to balance on what the
    hardware actually does — the FLOP model under-weights
    bandwidth-bound ops (pools, norms, cheap convs at high resolution),
    so measured balancing typically moves cuts earlier in CNNs.

    ``objective="bottleneck"`` delegates to the exact comm-aware solver
    (``defer_tpu.plan``): it minimizes ``max_k max(compute_k, comm_k)``
    instead of compute quantiles, which matters whenever a quantile cut
    lands on a fat activation boundary.  ``cost_model`` (a
    ``plan.StageCostModel``) customizes hardware/codec assumptions;
    otherwise an analytic model is built (using ``costs`` as measured
    node seconds when given).  The quantile greedy stays the default —
    it is the measurable baseline ``benchmarks/run.py`` compares against.
    """
    if num_stages < 1:
        raise ValueError("num_stages must be >= 1")
    if objective == "bottleneck":
        from ..plan import StageCostModel, solve
        if cost_model is None:
            cost_model = StageCostModel(graph, node_costs=costs)
        return solve(graph, num_stages, cost_model).cuts
    if objective != "quantile":
        raise ValueError(f"unknown objective {objective!r}; "
                         "use 'quantile' or 'bottleneck'")
    if num_stages == 1:
        return []
    cuts = valid_cut_points(graph)
    if len(cuts) < num_stages - 1:
        raise ValueError(
            f"graph {graph.name!r} has only {len(cuts)} valid cut points; "
            f"cannot make {num_stages} stages")

    order = graph.topo_order
    if costs is not None:
        missing = [n for n in order if n not in costs]
        if missing:
            raise ValueError(f"costs missing nodes: {missing[:5]}...")
    cum = {}
    acc = 0
    for name in order:
        acc += costs[name] if costs is not None else node_flops(graph, name)
        cum[name] = acc
    # guard ONLY exactly-zero totals: max(acc, 1) would clamp sub-1.0
    # measured-seconds sums to 1 and push every quantile target past the
    # end of the curve (collapsing all cuts to the tail)
    total = acc if acc > 0 else 1

    chosen: list[str] = []
    available = list(cuts)
    for j in range(1, num_stages):
        target = total * j / num_stages
        # nearest still-available cut by cumulative cost, keeping order —
        # restricted so enough candidates REMAIN for the later cuts (a
        # greedy pick near the tail could otherwise exhaust the pool;
        # skewed measured-cost maps hit this where the smooth FLOP model
        # rarely did)
        remaining_after = num_stages - 1 - j
        cands = available[: len(available) - remaining_after]
        best = min(cands, key=lambda n: abs(cum[n] - target))
        chosen.append(best)
        # drop this cut and everything before it to preserve ordering
        available = available[available.index(best) + 1:]
    return chosen


def max_activation_elems(graph: LayerGraph, cut_points: list[str]) -> int:
    """Largest per-sample tensor crossing any stage boundary (incl. graph
    input/output) — sizes the SPMD pipeline's homogeneous transfer buffer."""
    sizes = [graph.input_spec.size, graph.output_spec.size]
    sizes += [graph.out_spec(c).size for c in cut_points]
    return max(sizes)


def max_activation_bytes(graph: LayerGraph, cut_points: list[str], *,
                         batch: int = 1) -> int:
    """Largest boundary tensor in BYTES (dtype itemsize included, times
    ``batch``) — what one hop frame of a process chain actually weighs.
    ``max_activation_elems`` undercounts mixed-dtype graphs (an int32
    token boundary and an f32 activation boundary of equal ``size``
    differ on the wire); this is the number that sizes kernel socket
    buffers (``transport.framed.default_sock_buf``) and the planner's
    comm model."""
    specs = [graph.input_spec, graph.output_spec]
    specs += [graph.out_spec(c) for c in cut_points]
    return max(s.size * s.dtype.itemsize for s in specs) * max(batch, 1)
