"""Layer-graph IR: the model representation the partitioner operates on.

The reference framework introspects Keras graphs at runtime
(``model.get_layer(name).inbound_nodes`` — reference src/dag_util.py:3-7) to
rebuild sub-models between cut points.  JAX has no such runtime graph, so this
module *owns* the graph: models are built as an explicit DAG of named layer
nodes (op + input edges), and every downstream component (partitioner, stage
compiler, pipeline runtime) consumes this IR.

Design choices vs. the reference:
  * Graph structure is static and explicit — no runtime re-invocation of layer
    objects (reference src/dag_util.py:23-24).
  * Forward evaluation is memoized topological traversal, fixing the
    exponential re-visit of shared ancestors on branching DAGs
    (reference src/dag_util.py:16-17 has no memoization).
  * Parameters are a separate pytree keyed by node name, so the same graph
    can be initialized, loaded from checkpoint, cast, or sharded without
    touching structure.  Shapes are stored *batchless*; apply() is batched.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp

Params = Any  # pytree of arrays (or None for parameterless ops)


class ShapeSpec:
    """Batchless shape+dtype of one inter-layer tensor."""

    __slots__ = ("shape", "dtype")

    def __init__(self, shape: Sequence[int], dtype: Any = jnp.float32):
        self.shape = tuple(int(s) for s in shape)
        self.dtype = jnp.dtype(dtype)

    @property
    def size(self) -> int:
        n = 1
        for s in self.shape:
            n *= s
        return n

    def batched(self, batch: int) -> jax.ShapeDtypeStruct:
        return jax.ShapeDtypeStruct((batch,) + self.shape, self.dtype)

    def __repr__(self):
        return f"ShapeSpec({self.shape}, {self.dtype.name})"

    def __eq__(self, other):
        return (
            isinstance(other, ShapeSpec)
            and self.shape == other.shape
            and self.dtype == other.dtype
        )


class Op:
    """Base class for layer ops.

    Subclasses implement ``init`` (parameter construction from input shapes)
    and ``apply`` (batched forward).  ``apply`` must be pure and jit-safe.
    """

    def init(self, key: jax.Array, in_specs: tuple[ShapeSpec, ...]) -> Params:
        del key, in_specs
        return None

    def apply(self, params: Params, *xs: jax.Array) -> jax.Array:
        raise NotImplementedError

    def flops(self, in_specs: tuple[ShapeSpec, ...], out_spec: ShapeSpec) -> int:
        """Rough per-sample FLOP estimate, used for balanced auto-partition."""
        del in_specs
        return out_spec.size  # elementwise default

    # -- tensor parallelism (parallel/tensor.py) ---------------------------
    # Default: parameters replicated, apply unchanged.  Matmul-bearing ops
    # override both to shard weights over the "model" mesh axis.

    def tp_shard(self, params: Params, tp: int, rank: int) -> Params:
        """Rank ``rank``'s shard of ``params`` for ``tp``-way TP."""
        del tp, rank
        return params

    def tp_apply(self, params: Params, *xs: jax.Array,
                 axis_name: str | None = None, tp: int = 1) -> jax.Array:
        """Forward on TP-sharded params; must psum partial results."""
        del axis_name, tp
        return self.apply(params, *xs)

    def tp_unshard(self, shards: list[Params]) -> Params:
        """Inverse of :meth:`tp_shard`: all ranks' shards -> full params.
        Default (replicated params): every rank holds the full copy."""
        return shards[0]

    def __repr__(self):
        return type(self).__name__


@dataclasses.dataclass(frozen=True)
class LayerNode:
    name: str
    op: Op
    inputs: tuple[str, ...]
    out_spec: ShapeSpec
    param_spec: Any  # pytree of jax.ShapeDtypeStruct, or None


class LayerGraph:
    """A single-input single-output DAG of layer nodes in topological order.

    ``nodes`` is an insertion-ordered dict; the builder only appends a node
    after all of its inputs exist, so iteration order *is* a topological
    order.  This linearization is what partitioning cuts along (the
    reference's equivalent is the Keras layer list + ``inbound_nodes``).
    """

    def __init__(
        self,
        name: str,
        nodes: dict[str, LayerNode],
        input_name: str,
        output_name: str,
        input_spec: ShapeSpec,
    ):
        self.name = name
        self.nodes = nodes
        self.input_name = input_name
        self.output_name = output_name
        self.input_spec = input_spec

    # -- structure ---------------------------------------------------------

    @property
    def topo_order(self) -> list[str]:
        return list(self.nodes)

    def predecessors(self, name: str) -> tuple[str, ...]:
        """DEFER's ``get_previous`` (reference src/dag_util.py:3-7)."""
        return self.nodes[name].inputs

    def out_spec(self, name: str) -> ShapeSpec:
        if name == self.input_name:
            return self.input_spec
        return self.nodes[name].out_spec

    @property
    def output_spec(self) -> ShapeSpec:
        return self.out_spec(self.output_name)

    # -- parameters --------------------------------------------------------

    def init(self, key: jax.Array) -> dict[str, Params]:
        """Initialize a fresh parameter pytree keyed by node name."""
        params: dict[str, Params] = {}
        keys = jax.random.split(key, max(len(self.nodes), 1))
        for k, node in zip(keys, self.nodes.values()):
            if node.param_spec is None:
                continue
            in_specs = tuple(self.out_spec(i) for i in node.inputs)
            params[node.name] = node.op.init(k, in_specs)
        return params

    # -- evaluation --------------------------------------------------------

    def apply(
        self,
        params: dict[str, Params],
        x: jax.Array = None,
        *,
        upto: str | None = None,
        start: str | None = None,
        node_names: Sequence[str] | None = None,
        tp_axis: str | None = None,
        tp: int = 1,
        seeds: dict[str, jax.Array] | None = None,
    ) -> jax.Array:
        """Memoized forward pass over (a sub-range of) the graph.

        ``start``/``upto``/``node_names`` support stage evaluation: with
        ``start=c`` the cache is seeded with ``{c: x}`` and only
        ``node_names`` are evaluated.  This is the functional equivalent of
        the reference's ``construct_model(model, start, end)``
        (src/dag_util.py:27-31) without rebuilding any graph.

        ``seeds`` (name -> array) seeds the cache with SEVERAL boundary
        tensors instead of one ``start`` — how a join stage of a
        branched pipeline resumes evaluation from all of its merge op's
        inputs at once (``partition.stage.JoinStageSpec``).

        With ``tp_axis`` set (inside ``shard_map`` over a "model" mesh
        axis), each op runs its tensor-parallel path on TP-sharded params
        (see ``parallel/tensor.py``).
        """
        if x is None and seeds is None:
            raise TypeError("apply() needs an input array x (or seeds= "
                            "boundary tensors)")
        start = start or self.input_name
        upto = upto or self.output_name
        cache: dict[str, jax.Array] = (
            dict(seeds) if seeds is not None else {start: x})
        names = node_names if node_names is not None else self.topo_order
        for name in names:
            if name in cache:  # the seeded start node
                continue
            node = self.nodes[name]
            xs = [cache[i] for i in node.inputs]
            if tp_axis is not None and tp > 1:
                cache[name] = node.op.tp_apply(params.get(name), *xs,
                                               axis_name=tp_axis, tp=tp)
            else:
                cache[name] = node.op.apply(params.get(name), *xs)
            if name == upto:
                break
        return cache[upto]

    # -- derived graphs ----------------------------------------------------

    def with_input_shape(self, shape: Sequence[int],
                         dtype: Any = None) -> "LayerGraph":
        """Same ops/params, re-inferred specs for a new input shape.

        Ops must be shape-polymorphic in ``apply`` (true of the sequence
        ops: embeddings slice ``wpe[:t]``, attention masks derive from the
        runtime shape).  Parameters of the original graph remain valid —
        ``init`` specs are constructor-determined, not input-determined.
        Used by :meth:`Defer.score` to run short sequences through a
        short-length pipeline instead of padding to the full graph length.
        """
        spec = ShapeSpec(shape, dtype or self.input_spec.dtype)
        nodes: dict[str, LayerNode] = {}

        def spec_of(n: str) -> ShapeSpec:
            return spec if n == self.input_name else nodes[n].out_spec

        for name, node in self.nodes.items():
            in_specs = tuple(spec_of(i) for i in node.inputs)
            batched = [s.batched(1) for s in in_specs]
            out = jax.eval_shape(node.op.apply, node.param_spec, *batched)
            nodes[name] = LayerNode(name, node.op, node.inputs,
                                    ShapeSpec(out.shape[1:], out.dtype),
                                    node.param_spec)
        return LayerGraph(self.name, nodes, self.input_name,
                          self.output_name, spec)

    def __repr__(self):
        return f"LayerGraph({self.name!r}, {len(self.nodes)} nodes)"


class GraphBuilder:
    """Functional-style graph construction (the Keras-functional analogue).

    Shape inference runs eagerly at build time via ``jax.eval_shape`` so no
    parameters are materialized until ``graph.init(key)``.
    """

    def __init__(self, name: str):
        self.name = name
        self._nodes: dict[str, LayerNode] = {}
        self._input_name: str | None = None
        self._input_spec: ShapeSpec | None = None
        self._counts: dict[str, int] = {}
        self._last: str | None = None

    def input(self, shape: Sequence[int], dtype: Any = jnp.float32) -> str:
        if self._input_name is not None:
            raise ValueError("graph already has an input")
        self._input_name = "input"
        self._input_spec = ShapeSpec(shape, dtype)
        self._last = self._input_name
        return self._input_name

    def _auto_name(self, op: Op) -> str:
        base = type(op).__name__.lower()
        n = self._counts.get(base, 0)
        self._counts[base] = n + 1
        return f"{base}_{n}" if n else base

    def _spec_of(self, name: str) -> ShapeSpec:
        if name == self._input_name:
            assert self._input_spec is not None
            return self._input_spec
        return self._nodes[name].out_spec

    def add(
        self,
        op: Op,
        inputs: str | Sequence[str] | None = None,
        *,
        name: str | None = None,
    ) -> str:
        """Append a node; returns its name (usable as a cut point)."""
        if self._input_name is None:
            raise ValueError("call input() first")
        if inputs is None:
            inputs = [self._last]
        if isinstance(inputs, str):
            inputs = [inputs]
        inputs = tuple(inputs)
        for i in inputs:
            if i != self._input_name and i not in self._nodes:
                raise ValueError(f"unknown input node {i!r}")
        name = name or self._auto_name(op)
        if name in self._nodes or name == self._input_name:
            raise ValueError(f"duplicate node name {name!r}")

        in_specs = tuple(self._spec_of(i) for i in inputs)
        param_spec = jax.eval_shape(lambda k: op.init(k, in_specs),
                                    jax.ShapeDtypeStruct((2,), jnp.uint32))
        batched = [s.batched(1) for s in in_specs]
        out = jax.eval_shape(op.apply, param_spec, *batched)
        if not isinstance(out, jax.ShapeDtypeStruct) or not hasattr(out, "shape"):
            raise TypeError(f"op {op!r} must return a single array")
        out_spec = ShapeSpec(out.shape[1:], out.dtype)

        if jax.tree_util.tree_leaves(param_spec) == []:
            param_spec = None
        self._nodes[name] = LayerNode(name, op, inputs, out_spec, param_spec)
        self._last = name
        return name

    def build(self, output: str | None = None) -> LayerGraph:
        if self._input_name is None or not self._nodes:
            raise ValueError("empty graph")
        output = output or self._last
        assert self._input_spec is not None
        return LayerGraph(self.name, dict(self._nodes), self._input_name,
                          output, self._input_spec)
