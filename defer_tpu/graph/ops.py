"""Layer op library (NHWC, MXU-friendly).

These replace the Keras layer zoo the reference leans on (its compute is
entirely ``model.predict`` — reference src/node.py:106).  Conventions:

  * NHWC activations / HWIO kernels — the TPU-native conv layout.
  * Parameters are created in float32; ``apply`` computes in the incoming
    activation dtype (cast params down), so running the pipeline in bfloat16
    keeps the MXU fed without separate model definitions.
  * BatchNorm is inference-mode (folded running stats), matching DEFER's
    inference-only scope.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .ir import Op, ShapeSpec


def _cast(p, dtype):
    return jax.tree.map(lambda a: a.astype(dtype), p)


def _sym_pad(padding, *, nhwc: bool = False):
    """Normalize a padding spec: "SAME"/"VALID" pass through; an explicit
    symmetric ``(ph, pw)`` becomes lax pad pairs (spatial-only, or padded
    out to NHWC rank for reduce_window).  The ONE place the convention
    lives — conv, depthwise, and pooling all route through it."""
    if isinstance(padding, str):
        return padding
    ph, pw = padding
    pairs = ((ph, ph), (pw, pw))
    return ((0, 0), *pairs, (0, 0)) if nhwc else list(pairs)


# ---------------------------------------------------------------------------
# dense / conv
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True, repr=False)
class Dense(Op):
    features: int
    use_bias: bool = True

    def init(self, key, in_specs):
        (spec,) = in_specs
        d = spec.shape[-1]
        wkey, _ = jax.random.split(key)
        scale = 1.0 / math.sqrt(d)
        p = {"w": jax.random.uniform(wkey, (d, self.features), jnp.float32,
                                     -scale, scale)}
        if self.use_bias:
            p["b"] = jnp.zeros((self.features,), jnp.float32)
        return p

    def apply(self, params, x):
        p = _cast(params, x.dtype)
        y = x @ p["w"]
        if self.use_bias:
            y = y + p["b"]
        return y

    def flops(self, in_specs, out_spec):
        (spec,) = in_specs
        return 2 * spec.size * self.features

    # -- tensor parallelism: row-parallel (input dim sharded, one psum) ----

    def tp_shard(self, params, tp, rank):
        w = params["w"]
        d = w.shape[0]
        if d % tp:
            raise ValueError(f"Dense input dim {d} not divisible by tp={tp}")
        blk = d // tp
        out = {"w": w[rank * blk:(rank + 1) * blk]}
        if self.use_bias:
            out["b"] = params["b"]  # replicated; added once after the psum
        return out

    def tp_apply(self, params, x, *, axis_name=None, tp=1):
        if axis_name is None or tp == 1:
            return self.apply(params, x)
        p = _cast(params, x.dtype)
        blk = p["w"].shape[0]
        idx = lax.axis_index(axis_name)
        xs = lax.dynamic_slice_in_dim(x, idx * blk, blk, axis=x.ndim - 1)
        y = lax.psum(xs @ p["w"], axis_name)
        if self.use_bias:
            y = y + p["b"]
        return y

    def tp_unshard(self, shards):
        out = {"w": jnp.concatenate([s["w"] for s in shards], axis=0)}
        if self.use_bias:
            out["b"] = shards[0]["b"]  # replicated
        return out


@dataclasses.dataclass(frozen=True, repr=False)
class Conv2D(Op):
    features: int
    kernel: int | tuple[int, int] = 3
    stride: int | tuple[int, int] = 1
    #: "SAME"/"VALID", or an explicit symmetric (ph, pw) pad.  The tuple
    #: form exists for torch-trained weights: torch pads stride-2 convs
    #: symmetrically (k//2 each side) where XLA SAME pads (0, 1)-style
    #: asymmetrically — numerically different at every downsampling conv.
    padding: str | tuple[int, int] = "SAME"
    use_bias: bool = True
    groups: int = 1

    def _k(self):
        k = self.kernel
        return (k, k) if isinstance(k, int) else tuple(k)

    def _s(self):
        s = self.stride
        return (s, s) if isinstance(s, int) else tuple(s)

    def _p(self):
        return _sym_pad(self.padding)

    def init(self, key, in_specs):
        (spec,) = in_specs
        kh, kw = self._k()
        cin = spec.shape[-1]
        fan_in = kh * kw * cin // self.groups
        wkey, _ = jax.random.split(key)
        p = {"w": jax.random.normal(wkey, (kh, kw, cin // self.groups,
                                           self.features), jnp.float32)
             * math.sqrt(2.0 / fan_in)}
        if self.use_bias:
            p["b"] = jnp.zeros((self.features,), jnp.float32)
        return p

    def apply(self, params, x):
        p = _cast(params, x.dtype)
        y = lax.conv_general_dilated(
            x, p["w"], window_strides=self._s(), padding=self._p(),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=self.groups,
        )
        if self.use_bias:
            y = y + p["b"]
        return y

    def flops(self, in_specs, out_spec):
        (spec,) = in_specs
        kh, kw = self._k()
        cin = spec.shape[-1]
        return 2 * out_spec.size * kh * kw * cin // self.groups


@dataclasses.dataclass(frozen=True, repr=False)
class DepthwiseConv2D(Op):
    kernel: int = 3
    stride: int = 1
    #: "SAME"/"VALID" or explicit symmetric (ph, pw) — see Conv2D.padding
    padding: str | tuple[int, int] = "SAME"
    use_bias: bool = False  # enabled by the BatchNorm-folding pass

    def init(self, key, in_specs):
        (spec,) = in_specs
        c = spec.shape[-1]
        k = self.kernel
        p = {"w": jax.random.normal(key, (k, k, 1, c), jnp.float32)
             * math.sqrt(2.0 / (k * k))}
        if self.use_bias:
            p["b"] = jnp.zeros((c,), jnp.float32)
        return p

    def apply(self, params, x):
        p = _cast(params, x.dtype)
        c = x.shape[-1]
        y = lax.conv_general_dilated(
            x, p["w"], window_strides=(self.stride, self.stride),
            padding=_sym_pad(self.padding),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=c,
        )
        if self.use_bias:
            y = y + p["b"]
        return y

    def flops(self, in_specs, out_spec):
        return 2 * out_spec.size * self.kernel * self.kernel


# ---------------------------------------------------------------------------
# normalization
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True, repr=False)
class BatchNorm(Op):
    """Inference-mode batch norm (running statistics folded at apply)."""

    eps: float = 1e-5

    def init(self, key, in_specs):
        del key
        (spec,) = in_specs
        c = spec.shape[-1]
        return {
            "scale": jnp.ones((c,), jnp.float32),
            "bias": jnp.zeros((c,), jnp.float32),
            "mean": jnp.zeros((c,), jnp.float32),
            "var": jnp.ones((c,), jnp.float32),
        }

    def apply(self, params, x):
        p = _cast(params, x.dtype)
        inv = lax.rsqrt(p["var"] + jnp.asarray(self.eps, x.dtype))
        return (x - p["mean"]) * (inv * p["scale"]) + p["bias"]


@dataclasses.dataclass(frozen=True, repr=False)
class LayerNorm(Op):
    eps: float = 1e-6

    def init(self, key, in_specs):
        del key
        (spec,) = in_specs
        d = spec.shape[-1]
        return {"scale": jnp.ones((d,), jnp.float32),
                "bias": jnp.zeros((d,), jnp.float32)}

    def apply(self, params, x):
        p = _cast(params, x.dtype)
        mu = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
        return (x - mu) * lax.rsqrt(var + jnp.asarray(self.eps, x.dtype)) \
            * p["scale"] + p["bias"]


# ---------------------------------------------------------------------------
# activations / pooling / structural
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True, repr=False)
class Activation(Op):
    kind: str = "relu"  # relu | relu6 | gelu | swish | softmax | tanh

    def apply(self, params, x):
        del params
        if self.kind == "relu":
            return jax.nn.relu(x)
        if self.kind == "relu6":
            return jnp.minimum(jax.nn.relu(x), jnp.asarray(6, x.dtype))
        if self.kind == "gelu":
            return jax.nn.gelu(x)
        if self.kind == "swish":
            return jax.nn.swish(x)
        if self.kind == "softmax":
            return jax.nn.softmax(x, axis=-1)
        if self.kind == "tanh":
            return jnp.tanh(x)
        raise ValueError(self.kind)


@dataclasses.dataclass(frozen=True, repr=False)
class MaxPool(Op):
    window: int = 2
    stride: int | None = None
    #: "SAME"/"VALID" or explicit symmetric (ph, pw) — see Conv2D.padding
    padding: str | tuple[int, int] = "VALID"

    def apply(self, params, x):
        del params
        s = self.stride or self.window
        if jnp.issubdtype(x.dtype, jnp.floating):
            identity = -jnp.inf
        else:
            identity = jnp.iinfo(x.dtype).min
        return lax.reduce_window(
            x, identity, lax.max,
            (1, self.window, self.window, 1), (1, s, s, 1),
            _sym_pad(self.padding, nhwc=True))


@functools.lru_cache(maxsize=256)
def _window_counts(hw: tuple[int, int], window: int, stride: int,
                   padding: str) -> np.ndarray:
    """[1, H', W', 1] valid-element count per pooling window (XLA SAME/
    VALID semantics), as a host-side constant."""
    h, w = hw
    padding = padding.upper()  # lax accepts lowercase padding strings
    if padding == "VALID":
        oh = (h - window) // stride + 1
        ow = (w - window) // stride + 1
        return np.full((1, oh, ow, 1), float(window * window), np.float32)
    oh, ow = -(-h // stride), -(-w // stride)
    ph = max((oh - 1) * stride + window - h, 0)
    pw = max((ow - 1) * stride + window - w, 0)
    mask = np.zeros((h + ph, w + pw), np.float32)
    mask[ph // 2: ph // 2 + h, pw // 2: pw // 2 + w] = 1.0
    out = np.empty((oh, ow), np.float32)
    for i in range(oh):
        for j in range(ow):
            out[i, j] = mask[i * stride: i * stride + window,
                             j * stride: j * stride + window].sum()
    return out.reshape(1, oh, ow, 1)


@dataclasses.dataclass(frozen=True, repr=False)
class AvgPool(Op):
    window: int = 2
    stride: int | None = None
    padding: str = "VALID"
    #: True = divide by window**2 even where the window overlaps padding
    #: (torch ``avg_pool2d``'s default, used by torchvision InceptionV3's
    #: pool branches); False = divide by the valid-element count (XLA/
    #: Keras semantics).
    count_include_pad: bool = False

    def apply(self, params, x):
        del params
        s = self.stride or self.window
        # NOTE the init value must be a python scalar LITERAL: an array
        # init routes to the generic reduce_window primitive, whose remat
        # linearization fails under jax.grad(jax.checkpoint(...)) — the
        # literal routes to the dedicated (transposable) sum primitive
        summed = lax.reduce_window(x, 0.0, lax.add,
                                   (1, self.window, self.window, 1),
                                   (1, s, s, 1), self.padding)
        if self.count_include_pad:
            return summed / jnp.asarray(self.window * self.window, x.dtype)
        # window counts depend only on static shape/padding: bake them in
        # as a numpy constant
        counts = _window_counts(x.shape[1:3], self.window, s, self.padding)
        return summed / jnp.asarray(counts, x.dtype)


@dataclasses.dataclass(frozen=True, repr=False)
class GlobalAvgPool(Op):
    def apply(self, params, x):
        del params
        return jnp.mean(x, axis=(1, 2))


@dataclasses.dataclass(frozen=True, repr=False)
class ZeroPad2D(Op):
    pad: int = 1

    def apply(self, params, x):
        del params
        p = self.pad
        return jnp.pad(x, ((0, 0), (p, p), (p, p), (0, 0)))


@dataclasses.dataclass(frozen=True, repr=False)
class Add(Op):
    """Residual merge — DEFER's canonical cut-point layer (its ResNet50
    benchmark cuts only at ``add_*`` layers, reference test/test.py:18)."""

    def apply(self, params, *xs):
        del params
        y = xs[0]
        for x in xs[1:]:
            y = y + x
        return y


@dataclasses.dataclass(frozen=True, repr=False)
class Concat(Op):
    axis: int = -1

    def apply(self, params, *xs):
        del params
        return jnp.concatenate(xs, axis=self.axis)


@dataclasses.dataclass(frozen=True, repr=False)
class Flatten(Op):
    def apply(self, params, x):
        del params
        return x.reshape(x.shape[0], -1)


@dataclasses.dataclass(frozen=True, repr=False)
class Tile(Op):
    """Repeat the per-sample input ``reps`` times along a new leading
    axis — a cheap FAT-activation producer (output bytes = reps x input
    bytes for one broadcast write).  Bench models for copy-bound
    transport work (``scripts/ici_smoke.py``) use it to make a boundary
    tensor fat without making the compute expensive."""

    reps: int = 2

    def apply(self, params, x):
        del params
        return jnp.broadcast_to(
            x[:, None, ...], (x.shape[0], self.reps) + x.shape[1:])


@dataclasses.dataclass(frozen=True, repr=False)
class Cast(Op):
    """Element dtype cast (e.g. to ``bfloat16`` — the TPU-native
    activation regime, where a host round-trip pays a real
    materialization the device-resident path skips)."""

    dtype: str = "bfloat16"

    def apply(self, params, x):
        del params
        return x.astype(self.dtype)


@dataclasses.dataclass(frozen=True, repr=False)
class ReduceMean(Op):
    """Mean over one per-sample axis — the matching fat-activation
    consumer (one read pass, thin output)."""

    axis: int = 1

    def apply(self, params, x):
        del params
        return jnp.mean(x, axis=self.axis)

    def flops(self, in_specs, out_spec):
        (spec,) = in_specs
        return spec.size  # one add per reduced element


@dataclasses.dataclass(frozen=True, repr=False)
class Embedding(Op):
    vocab: int
    features: int

    def init(self, key, in_specs):
        del in_specs
        return {"table": jax.random.normal(key, (self.vocab, self.features),
                                           jnp.float32) * 0.02}

    def apply(self, params, x):
        return params["table"].astype(jnp.float32)[x.astype(jnp.int32)]


# ---------------------------------------------------------------------------
# transformer block (one node per block ⇒ natural BERT cut points)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True, repr=False)
class TransformerBlock(Op):
    """Pre-LN transformer encoder block as a single graph node.

    Modeling each block as one node mirrors how the BERT-Base/12 baseline
    config places one block per pipeline stage (BASELINE.md config 5); every
    block output is automatically a valid single-tensor cut point.
    """

    num_heads: int
    mlp_ratio: int = 4
    #: "auto" = Pallas flash attention on TPU / plain XLA elsewhere;
    #: "flash" and "xla" force one implementation
    attn_impl: str = "auto"
    #: "pre" (GPT-style: x + f(LN(x))) or "post" (original-BERT style:
    #: LN(x + f(x))) — post is required for faithful import of HF BERT
    #: checkpoints, whose weights were trained under post-LN residuals
    norm: str = "pre"
    ln_eps: float = 1e-6

    def __post_init__(self):
        if self.norm not in ("pre", "post"):  # one check covers BOTH the
            # plain and the tensor-parallel forward paths
            raise ValueError(
                f"norm must be 'pre' or 'post', got {self.norm!r}")

    def init(self, key, in_specs):
        (spec,) = in_specs
        d = spec.shape[-1]
        h = self.mlp_ratio * d
        ks = jax.random.split(key, 6)
        s = 1.0 / math.sqrt(d)
        return {
            "ln1": {"scale": jnp.ones((d,), jnp.float32),
                    "bias": jnp.zeros((d,), jnp.float32)},
            "qkv": {"w": jax.random.normal(ks[0], (d, 3 * d), jnp.float32) * s,
                    "b": jnp.zeros((3 * d,), jnp.float32)},
            "proj": {"w": jax.random.normal(ks[1], (d, d), jnp.float32) * s,
                     "b": jnp.zeros((d,), jnp.float32)},
            "ln2": {"scale": jnp.ones((d,), jnp.float32),
                    "bias": jnp.zeros((d,), jnp.float32)},
            "fc1": {"w": jax.random.normal(ks[2], (d, h), jnp.float32) * s,
                    "b": jnp.zeros((h,), jnp.float32)},
            "fc2": {"w": jax.random.normal(ks[3], (h, d), jnp.float32)
                    * (1.0 / math.sqrt(h)),
                    "b": jnp.zeros((d,), jnp.float32)},
        }

    @staticmethod
    def _ln(p, x, eps=1e-6):
        mu = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
        return (x - mu) * lax.rsqrt(var + jnp.asarray(eps, x.dtype)) \
            * p["scale"] + p["bias"]

    def _attend(self, q, k, v):
        """Scaled-dot-product attention on [b, nh, t, hd] (impl dispatch)."""
        impl = self.attn_impl
        if impl == "auto":
            impl = "flash" if jax.default_backend() == "tpu" else "xla"
        if impl not in ("flash", "xla"):
            raise ValueError(
                f"attn_impl must be 'auto', 'flash' or 'xla', got {impl!r}")
        if impl == "flash":
            from ..ops import flash_attention
            return flash_attention(q, k, v)
        hd = q.shape[-1]
        att = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(hd)
        att = jax.nn.softmax(att, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", att, v)

    def _split_qkv(self, qkv):
        """q/k/v column split of the fused projection (subclass hook)."""
        return jnp.split(qkv, 3, axis=-1)

    def _kv_head_count(self) -> int:
        """KV head count (subclass hook; GQA blocks return fewer)."""
        return self.num_heads

    def apply(self, params, x):
        return self.apply_with_kv(params, x)[0]

    def apply_with_kv(self, params, x):
        """Forward that also returns the raw K/V projections.

        The single definition of the block forward — ``apply`` discards the
        byproducts (XLA dead-code-eliminates them); decode-cache seeding
        (models/gpt.py prefill) consumes them.  K/V are [b, t, kv*hd]
        pre-head-split columns (kv == num_heads unless a GQA subclass
        narrows them).
        """
        p = _cast(params, x.dtype)
        b, t, d = x.shape
        nh = self.num_heads
        hd = d // nh
        kvh = self._kv_head_count()
        eps = self.ln_eps
        post = self.norm == "post"  # validated in __post_init__

        y = x if post else self._ln(p["ln1"], x, eps)
        qkv = y @ p["qkv"]["w"] + p["qkv"]["b"]
        q, k, v = self._split_qkv(qkv)
        qh = q.reshape(b, t, nh, hd).transpose(0, 2, 1, 3)
        kh = k.reshape(b, t, kvh, hd).transpose(0, 2, 1, 3)
        vh = v.reshape(b, t, kvh, hd).transpose(0, 2, 1, 3)
        if kvh != nh:
            # broadcast each KV head over its query group (exact GQA)
            kh = jnp.repeat(kh, nh // kvh, axis=1)
            vh = jnp.repeat(vh, nh // kvh, axis=1)
        y = self._attend(qh, kh, vh)
        y = y.transpose(0, 2, 1, 3).reshape(b, t, d)
        y = y @ p["proj"]["w"] + p["proj"]["b"]
        x = self._ln(p["ln1"], x + y, eps) if post else x + y

        y = x if post else self._ln(p["ln2"], x, eps)
        # post-LN (BERT) uses the exact erf GELU like HF; pre-LN keeps
        # the tanh approximation (GPT-2 convention, existing behavior)
        y = jax.nn.gelu(y @ p["fc1"]["w"] + p["fc1"]["b"],
                        approximate=not post)
        y = y @ p["fc2"]["w"] + p["fc2"]["b"]
        out = self._ln(p["ln2"], x + y, eps) if post else x + y
        return out, k, v

    def flops(self, in_specs, out_spec):
        (spec,) = in_specs
        t, d = spec.shape
        return 2 * t * d * (4 * d + 2 * self.mlp_ratio * d) + 4 * t * t * d

    # -- tensor parallelism: Megatron column->row pairing, heads sharded ---

    def tp_shard(self, params, tp, rank):
        nh, kv = self.num_heads, self._kv_head_count()
        if nh % tp or kv % tp:
            raise ValueError(
                f"heads={nh}/kv_heads={kv} not divisible by tp={tp} "
                f"(each rank must hold whole query groups)")
        d = params["qkv"]["w"].shape[0]
        hd = d // nh
        blk = d // tp                 # query columns per rank
        kvblk = (kv // tp) * hd       # K (and V) columns per rank
        # fused layout: [q (nh*hd) | k (kv*hd) | v (kv*hd)]; kv == nh
        # reduces to the classic Megatron equal-thirds slice
        q0, k0, v0 = 0, d, d + kv * hd

        def qkv_cols(a):
            # per-chunk column slice so each rank gets whole (query) heads
            return jnp.concatenate(
                [a[..., q0 + rank * blk: q0 + (rank + 1) * blk],
                 a[..., k0 + rank * kvblk: k0 + (rank + 1) * kvblk],
                 a[..., v0 + rank * kvblk: v0 + (rank + 1) * kvblk]],
                axis=-1)

        return {
            "qkv": {"w": qkv_cols(params["qkv"]["w"]),
                    "b": qkv_cols(params["qkv"]["b"])},
            **self._tp_shard_common(params, tp, rank),
        }

    def _tp_shard_common(self, params, tp, rank):
        """The non-qkv Megatron shards (LNs replicated, proj rows, MLP
        column->row pair) — shared by the MHA and GQA qkv schemes."""
        d = params["qkv"]["w"].shape[0]
        h = params["fc1"]["w"].shape[1]
        if h % tp:
            raise ValueError(f"mlp width {h} not divisible by tp={tp}")
        blk, hblk = d // tp, h // tp
        return {
            "ln1": params["ln1"],
            "proj": {"w": params["proj"]["w"][rank * blk:(rank + 1) * blk],
                     "b": params["proj"]["b"]},
            "ln2": params["ln2"],
            "fc1": {"w": params["fc1"]["w"][:, rank * hblk:(rank + 1) * hblk],
                    "b": params["fc1"]["b"][rank * hblk:(rank + 1) * hblk]},
            "fc2": {"w": params["fc2"]["w"][rank * hblk:(rank + 1) * hblk],
                    "b": params["fc2"]["b"]},
        }

    def tp_unshard(self, shards):
        """Inverse of :meth:`tp_shard`: concatenate each rank's query/K/V
        column groups back into the fused layout, proj/fc2 rows and fc1
        columns back to full width; LNs and biases are replicated."""
        tp = len(shards)
        nh, kv = self.num_heads, self._kv_head_count()
        d = shards[0]["proj"]["w"].shape[1]
        hd = d // nh
        blk, kvblk = d // tp, (kv // tp) * hd

        def qkv_cat(key):
            qs, ks, vs = [], [], []
            for sh in shards:
                a = sh["qkv"][key]
                qs.append(a[..., :blk])
                ks.append(a[..., blk: blk + kvblk])
                vs.append(a[..., blk + kvblk:])
            return jnp.concatenate(qs + ks + vs, axis=-1)

        return {
            "ln1": shards[0]["ln1"],
            "qkv": {"w": qkv_cat("w"), "b": qkv_cat("b")},
            "proj": {"w": jnp.concatenate(
                [sh["proj"]["w"] for sh in shards], axis=0),
                "b": shards[0]["proj"]["b"]},
            "ln2": shards[0]["ln2"],
            "fc1": {"w": jnp.concatenate(
                [sh["fc1"]["w"] for sh in shards], axis=1),
                "b": jnp.concatenate(
                    [sh["fc1"]["b"] for sh in shards], axis=0)},
            "fc2": {"w": jnp.concatenate(
                [sh["fc2"]["w"] for sh in shards], axis=0),
                "b": shards[0]["fc2"]["b"]},
        }

    def tp_apply(self, params, x, *, axis_name=None, tp=1):
        if axis_name is None or tp == 1:
            return self.apply(params, x)
        p = _cast(params, x.dtype)
        b, t, d = x.shape
        nh = self.num_heads // tp           # local query heads
        kvl = self._kv_head_count() // tp   # local KV heads (GQA: fewer)
        hd = d // self.num_heads
        dl = nh * hd                        # local query width d/tp
        eps = self.ln_eps
        post = self.norm == "post"          # mirror apply_with_kv exactly

        y = x if post else self._ln(p["ln1"], x, eps)
        qkv = y @ p["qkv"]["w"] + p["qkv"]["b"]
        q = qkv[..., :dl]
        k = qkv[..., dl: dl + kvl * hd]
        v = qkv[..., dl + kvl * hd:]
        q = q.reshape(b, t, nh, hd).transpose(0, 2, 1, 3)
        k = k.reshape(b, t, kvl, hd).transpose(0, 2, 1, 3)
        v = v.reshape(b, t, kvl, hd).transpose(0, 2, 1, 3)
        if kvl != nh:
            # broadcast each local KV head over its query group
            k = jnp.repeat(k, nh // kvl, axis=1)
            v = jnp.repeat(v, nh // kvl, axis=1)
        y = self._attend(q, k, v)
        y = y.transpose(0, 2, 1, 3).reshape(b, t, dl)
        y = lax.psum(y @ p["proj"]["w"], axis_name) + p["proj"]["b"]
        x = self._ln(p["ln1"], x + y, eps) if post else x + y

        y = x if post else self._ln(p["ln2"], x, eps)
        y = jax.nn.gelu(y @ p["fc1"]["w"] + p["fc1"]["b"],
                        approximate=not post)
        y = lax.psum(y @ p["fc2"]["w"], axis_name) + p["fc2"]["b"]
        return self._ln(p["ln2"], x + y, eps) if post else x + y


# ---------------------------------------------------------------------------
# mixture of experts (expert parallelism rides parallel/expert.py)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True, repr=False)
class MoE(Op):
    """Switch-style top-1 mixture-of-experts FFN (with residual).

    Single-device ``apply`` evaluates every expert and masks (exact, fine
    for the MXU at small E); the expert-parallel path — experts sharded over
    an "expert" mesh axis with capacity-based ``all_to_all`` token dispatch —
    lives in :mod:`defer_tpu.parallel.expert` and is numerically identical
    whenever no token exceeds capacity.
    """

    num_experts: int
    hidden: int

    def init(self, key, in_specs):
        (spec,) = in_specs
        d = spec.shape[-1]
        e, h = self.num_experts, self.hidden
        ks = jax.random.split(key, 3)
        return {
            "gate": jax.random.normal(ks[0], (d, e), jnp.float32) * 0.02,
            "fc1": {"w": jax.random.normal(ks[1], (e, d, h), jnp.float32)
                    / math.sqrt(d),
                    "b": jnp.zeros((e, h), jnp.float32)},
            "fc2": {"w": jax.random.normal(ks[2], (e, h, d), jnp.float32)
                    / math.sqrt(h),
                    "b": jnp.zeros((e, d), jnp.float32)},
        }

    def route(self, params, x):
        """Top-1 routing: (expert_id [b,t], gate_prob [b,t])."""
        logits = x @ params["gate"].astype(x.dtype)
        probs = jax.nn.softmax(logits, axis=-1)
        eid = jnp.argmax(logits, axis=-1)
        pe = jnp.take_along_axis(probs, eid[..., None], axis=-1)[..., 0]
        return eid, pe

    def expert_fn(self, params, x, eid):
        """Run expert ``eid`` (array, broadcastable) on tokens ``x``.

        ``params`` holds stacked expert weights [E_local, ...]; ``eid``
        indexes into that local stack.
        """
        fc1 = params["fc1"]
        fc2 = params["fc2"]
        w1 = fc1["w"][eid].astype(x.dtype)
        b1 = fc1["b"][eid].astype(x.dtype)
        w2 = fc2["w"][eid].astype(x.dtype)
        b2 = fc2["b"][eid].astype(x.dtype)
        h = jax.nn.gelu(jnp.einsum("...d,...dh->...h", x, w1) + b1)
        return jnp.einsum("...h,...hd->...d", h, w2) + b2

    def apply(self, params, x):
        eid, pe = self.route(params, x)
        b, t, d = x.shape
        e = self.num_experts
        h1 = jax.nn.gelu(
            jnp.einsum("btd,edh->bteh", x, params["fc1"]["w"].astype(x.dtype))
            + params["fc1"]["b"].astype(x.dtype))
        y = (jnp.einsum("bteh,ehd->bted", h1,
                        params["fc2"]["w"].astype(x.dtype))
             + params["fc2"]["b"].astype(x.dtype))
        sel = jax.nn.one_hot(eid, e, dtype=x.dtype)
        return x + (y * sel[..., None]).sum(axis=2) * pe[..., None]

    def flops(self, in_specs, out_spec):
        (spec,) = in_specs
        t, d = spec.shape
        # effective top-1 cost: one expert per token
        return 2 * t * d * (2 * self.hidden) + 2 * t * d * self.num_experts


@dataclasses.dataclass(frozen=True, repr=False)
class ExpertBranch(Op):
    """One expert's BRANCH of a branched mixture-of-experts layer.

    Where :class:`MoE` evaluates every expert inside one op (and the
    expert-parallel path shards them over a mesh axis,
    ``parallel/expert.py``), the branched formulation puts each expert
    on its own GRAPH branch so the DAG pipeline can place it on its own
    node: every branch reads the full block output (the fork tensor),
    computes its own softmax gate weight and expert FFN, and emits
    ``probs[..., expert] * ffn_e(x)``; the region's join is a plain
    :class:`Add` over the residual skip and all expert branches, so the
    merged output is the SOFT mixture ``x + sum_e p_e(x) * ffn_e(x)``.

    Soft (dense) gating on purpose: each branch re-derives its gate
    weight from its own replicated gate matrix, so branches stay
    self-contained single-input ops — a shared top-1 router would need a
    second tensor crossing the fork, which the single-tensor-cut
    transport does not carry.  Per-branch cost is one expert's FFN, the
    quantity expert-parallel placement divides.
    """

    num_experts: int
    expert: int
    hidden: int

    def init(self, key, in_specs):
        (spec,) = in_specs
        d = spec.shape[-1]
        ks = jax.random.split(key, 3)
        return {
            # the gate is replicated per branch and seeded by the
            # branch's OWN init key: gate weights differ across branches
            # by construction, which is fine for the soft mixture (each
            # branch's scalar weight is its own function of x)
            "gate": jax.random.normal(ks[0], (d, self.num_experts),
                                      jnp.float32) * 0.02,
            "fc1": {"w": jax.random.normal(ks[1], (d, self.hidden),
                                           jnp.float32) / math.sqrt(d),
                    "b": jnp.zeros((self.hidden,), jnp.float32)},
            "fc2": {"w": jax.random.normal(ks[2], (self.hidden, d),
                                           jnp.float32)
                    / math.sqrt(self.hidden),
                    "b": jnp.zeros((d,), jnp.float32)},
        }

    def apply(self, params, x):
        logits = x @ params["gate"].astype(x.dtype)
        pe = jax.nn.softmax(logits, axis=-1)[..., self.expert]
        h = jax.nn.gelu(x @ params["fc1"]["w"].astype(x.dtype)
                        + params["fc1"]["b"].astype(x.dtype))
        y = h @ params["fc2"]["w"].astype(x.dtype) \
            + params["fc2"]["b"].astype(x.dtype)
        return y * pe[..., None]

    def flops(self, in_specs, out_spec):
        (spec,) = in_specs
        t, d = spec.shape
        return 2 * t * d * (2 * self.hidden) + 2 * t * d * self.num_experts
