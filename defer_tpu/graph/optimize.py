"""Graph optimization passes (deployment-time rewrites).

The reference ships partitions exactly as authored (reference
src/dispatcher.py:40-49); a framework that owns its graph IR can rewrite
it before compilation.  First pass: **BatchNorm folding** — inference-mode
batch norm is an affine map per channel, so it folds exactly into the
preceding convolution's weights and bias, removing the op (and its HBM
round trip wherever XLA would not have fused it) from every stage program.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import numpy as np

import jax
import jax.numpy as jnp

from .ir import LayerGraph, LayerNode
from .ops import BatchNorm, Conv2D, DepthwiseConv2D


def _consumers(graph: LayerGraph, name: str) -> list[str]:
    return [n.name for n in graph.nodes.values() if name in n.inputs]


def fold_batchnorm(graph: LayerGraph, params: dict[str, Any]
                   ) -> tuple[LayerGraph, dict[str, Any], int]:
    """Fold inference BatchNorm into the preceding (depthwise) conv.

    For every ``conv -> bn`` pair where the conv output feeds ONLY the bn
    (and is not the graph output), rewrites

        bn(conv(x)) == conv'(x),  w' = w * g/sqrt(v+eps),
                                  b' = (b - mean) * g/sqrt(v+eps) + beta

    exactly (f32 arithmetic), drops the bn node, and rewires its
    consumers.  Returns ``(new_graph, new_params, folded_count)``; the
    inputs are left untouched.
    """
    nodes = dict(graph.nodes)
    new_params = dict(params)
    rename: dict[str, str] = {}  # bn name -> conv name
    folded = 0

    for bn_name, bn_node in graph.nodes.items():
        if not isinstance(bn_node.op, BatchNorm):
            continue
        (src,) = bn_node.inputs
        conv_node = nodes.get(src)
        if conv_node is None:  # graph input feeds the bn
            continue
        if not isinstance(conv_node.op, (Conv2D, DepthwiseConv2D)):
            continue
        if len(_consumers(graph, src)) != 1 or graph.output_name == src:
            continue

        bnp = params[bn_name]
        inv = np.asarray(bnp["scale"], np.float64) / np.sqrt(
            np.asarray(bnp["var"], np.float64) + bn_node.op.eps)
        cp = dict(params[src])
        w = np.asarray(cp["w"], np.float64)
        cp["w"] = (w * inv).astype(np.float32)  # out-channel dim is last
        b = np.asarray(cp.get("b", np.zeros(w.shape[-1])), np.float64)
        cp["b"] = ((b - np.asarray(bnp["mean"], np.float64)) * inv
                   + np.asarray(bnp["bias"], np.float64)).astype(np.float32)

        op = dataclasses.replace(conv_node.op, use_bias=True)
        param_spec = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(np.shape(a), jnp.float32), cp)
        nodes[src] = LayerNode(src, op, conv_node.inputs,
                               conv_node.out_spec, param_spec)
        new_params[src] = cp
        del nodes[bn_name]
        new_params.pop(bn_name, None)
        rename[bn_name] = src
        folded += 1

    if not folded:
        return graph, params, 0

    # rewire consumers of removed bn nodes (chase chains of renames)
    def resolve(name: str) -> str:
        while name in rename:
            name = rename[name]
        return name

    rewired = {}
    for name, node in nodes.items():
        inputs = tuple(resolve(i) for i in node.inputs)
        if inputs != node.inputs:
            node = LayerNode(name, node.op, inputs, node.out_spec,
                             node.param_spec)
        rewired[name] = node

    out = LayerGraph(graph.name + "+bnfold", rewired, graph.input_name,
                     resolve(graph.output_name), graph.input_spec)
    return out, new_params, folded
