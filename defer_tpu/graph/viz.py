"""Graph/partition visualization.

Parity with the reference's per-node diagnostic rendering
(``tf.keras.utils.plot_model(md, f"model_{ip}.png")`` — reference
src/node.py:39), done dependency-free: Graphviz DOT text and a column summary.
"""

from __future__ import annotations

from .analysis import node_flops
from .ir import LayerGraph


def to_dot(graph: LayerGraph, stage_of: dict[str, int] | None = None) -> str:
    """Render the layer graph as Graphviz DOT; optional stage coloring."""
    palette = ["#a6cee3", "#b2df8a", "#fb9a99", "#fdbf6f", "#cab2d6",
               "#ffff99", "#1f78b4", "#33a02c", "#e31a1c", "#ff7f00"]
    lines = [f'digraph "{graph.name}" {{', "  rankdir=TB;",
             "  node [shape=box, style=filled, fillcolor=white];",
             f'  "{graph.input_name}" [fillcolor="#eeeeee"];']
    for name, node in graph.nodes.items():
        label = f"{name}\\n{type(node.op).__name__} {node.out_spec.shape}"
        color = ""
        if stage_of is not None and name in stage_of:
            color = f', fillcolor="{palette[stage_of[name] % len(palette)]}"'
        lines.append(f'  "{name}" [label="{label}"{color}];')
        for src in node.inputs:
            lines.append(f'  "{src}" -> "{name}";')
    lines.append("}")
    return "\n".join(lines)


def summary(graph: LayerGraph) -> str:
    """Keras-``model.summary()``-style table."""
    rows = [("node", "op", "inputs", "out_shape", "MFLOPs")]
    for name, node in graph.nodes.items():
        rows.append((name, type(node.op).__name__, ",".join(node.inputs),
                     str(node.out_spec.shape),
                     f"{node_flops(graph, name) / 1e6:.2f}"))
    widths = [max(len(r[i]) for r in rows) for i in range(5)]
    out = [f"LayerGraph {graph.name!r}  input={graph.input_spec.shape}"]
    for r in rows:
        out.append("  ".join(c.ljust(w) for c, w in zip(r, widths)))
    return "\n".join(out)
