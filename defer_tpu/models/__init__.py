"""Model zoo covering the five BASELINE.md benchmark configs."""

from .resnet import (RESNET50_8STAGE_CUTS, resnet, resnet50, resnet_tiny)

__all__ = ["resnet", "resnet50", "resnet_tiny", "RESNET50_8STAGE_CUTS"]
