"""Model zoo covering the five BASELINE.md benchmark configs:

1. ResNet50/8   (reference test/test.py flagship)
2. VGG19/4      (deep sequential, large activations)
3. InceptionV3/6 (branching DAG)
4. MobileNetV2/2 (comm-bound)
5. BERT-Base/12 (one transformer block per stage)

Each family ships a ``*_tiny`` variant for fast CPU-mesh tests.
"""

from .bert import BERT_BASE_12STAGE_CUTS, bert, bert_base, bert_tiny
from .gpt import gpt, gpt2_small, gpt_small, gpt_stage_cuts, gpt_tiny
from .moe import (moe_branched, moe_branched_tiny, moe_stage_cuts,
                  moe_tiny, moe_transformer)
from .inception import (INCEPTION_6STAGE_CUTS, inception, inception_tiny,
                        inception_v3)
from .mobilenet import (MOBILENETV2_2STAGE_CUTS, mobilenet_tiny, mobilenet_v2)
from .resnet import RESNET50_8STAGE_CUTS, resnet, resnet50, resnet_tiny
from .vgg import VGG19_4STAGE_CUTS, vgg, vgg19, vgg_tiny

__all__ = [
    "resnet", "resnet50", "resnet_tiny", "RESNET50_8STAGE_CUTS",
    "vgg", "vgg19", "vgg_tiny", "VGG19_4STAGE_CUTS",
    "inception", "inception_v3", "inception_tiny", "INCEPTION_6STAGE_CUTS",
    "mobilenet_v2", "mobilenet_tiny", "MOBILENETV2_2STAGE_CUTS",
    "bert", "bert_base", "bert_tiny", "BERT_BASE_12STAGE_CUTS",
    "gpt", "gpt2_small", "gpt_small", "gpt_tiny", "gpt_stage_cuts",
    "moe_transformer", "moe_tiny", "moe_stage_cuts",
    "moe_branched", "moe_branched_tiny",
]
