"""BERT-Base encoder (BASELINE.md config 5: 12 partitions, one transformer
block per pipeline stage).

Each encoder block is a single graph node (``ops.TransformerBlock``), so
``block_k`` nodes are the natural cut points and the 12-stage config is just
``cut_points=[block_0 .. block_10]``.  Token-id inputs ride the pipeline's
float32 transfer buffer exactly (ids < 2^24).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..graph.ir import GraphBuilder, LayerGraph, Op, ShapeSpec
from ..graph.ops import TransformerBlock


class BertEmbedding(Op):
    """Token + learned positional embeddings, followed by layer norm.

    HF's segment (token-type) embedding is not a separate table here: for
    single-segment inputs it is a constant vector added pre-LN, so the
    importer folds ``token_type_embeddings[0]`` into ``pos`` exactly.
    """

    def __init__(self, vocab: int, features: int, max_len: int,
                 eps: float = 1e-12):
        self.vocab = vocab
        self.features = features
        self.max_len = max_len
        self.eps = eps

    def init(self, key, in_specs):
        (spec,) = in_specs
        k1, k2 = jax.random.split(key)
        return {
            "tok": jax.random.normal(k1, (self.vocab, self.features),
                                     jnp.float32) * 0.02,
            "pos": jax.random.normal(k2, (self.max_len, self.features),
                                     jnp.float32) * 0.02,
            "ln": {"scale": jnp.ones((self.features,), jnp.float32),
                   "bias": jnp.zeros((self.features,), jnp.float32)},
        }

    def apply(self, params, ids):
        t = ids.shape[1]
        x = params["tok"][ids.astype(jnp.int32)] + params["pos"][:t]
        mu = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
        ln = params["ln"]
        return (x - mu) * jax.lax.rsqrt(var + self.eps) \
            * ln["scale"] + ln["bias"]

    def flops(self, in_specs, out_spec):
        return out_spec.size


class Pooler(Op):
    """[CLS] pooling + tanh projection (BERT's pooler head)."""

    def __init__(self, features: int):
        self.features = features

    def init(self, key, in_specs):
        (spec,) = in_specs
        d = spec.shape[-1]
        return {"w": jax.random.normal(key, (d, self.features), jnp.float32)
                / math.sqrt(d),
                "b": jnp.zeros((self.features,), jnp.float32)}

    def apply(self, params, x):
        cls = x[:, 0, :]
        return jnp.tanh(cls @ params["w"].astype(x.dtype)
                        + params["b"].astype(x.dtype))

    def flops(self, in_specs, out_spec):
        (spec,) = in_specs
        return 2 * spec.shape[-1] * self.features


def bert(num_layers: int, hidden: int, heads: int, seq_len: int,
         vocab: int = 30522, name: str = "bert") -> LayerGraph:
    """Faithful original-BERT encoder: post-LN residual blocks with exact
    GELU and eps=1e-12 (matching HF ``bert-base-uncased``), no trailing
    LayerNorm (post-LN blocks end normalized) — so HF checkpoints import
    with matching semantics, not just matching shapes."""
    b = GraphBuilder(name)
    x = b.input((seq_len,), jnp.int32)
    x = b.add(BertEmbedding(vocab, hidden, seq_len), x, name="embeddings")
    for i in range(num_layers):
        x = b.add(TransformerBlock(heads, norm="post", ln_eps=1e-12),
                  x, name=f"block_{i}")
    x = b.add(Pooler(hidden), x, name="pooler")
    return b.build()


def bert_base(seq_len: int = 128) -> LayerGraph:
    return bert(12, 768, 12, seq_len, name="bert_base")


def bert_tiny(seq_len: int = 16) -> LayerGraph:
    return bert(4, 32, 2, seq_len, vocab=100, name="bert_tiny")


#: one encoder block per stage (BASELINE.md config 5): 12 stages — stage 0
#: holds embeddings + block_0, stage 11 holds block_11 + pooler
BERT_BASE_12STAGE_CUTS = [f"block_{i}" for i in range(11)]
