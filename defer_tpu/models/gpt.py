"""GPT-style causal decoder family: full-sequence graph + KV-cache decode.

The reference framework is CNN-only inference (SURVEY.md §2.3); this family
goes beyond parity: an autoregressive decoder whose full-sequence
(prefill/scoring) forward rides the ordinary ``SpmdPipeline`` — one
``block_k`` node per pipeline stage, exactly like BERT-Base/12 — and whose
token-by-token generation path is served by the pipelined KV-cache engine in
:mod:`defer_tpu.runtime.decode`.

Each :class:`CausalTransformerBlock` is one graph node (a natural
single-tensor cut point) and additionally exposes :meth:`decode` — the
single-token step against a key/value cache that the decode engine switches
on per stage.
"""

from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp
from jax import lax

from ..graph.ir import GraphBuilder, LayerGraph, Op
from ..graph.ops import Dense, LayerNorm, TransformerBlock, _cast


@dataclasses.dataclass(frozen=True, repr=False)
class CausalTransformerBlock(TransformerBlock):
    """Pre-LN decoder block: causal self-attention + MLP.

    Full-sequence ``apply`` masks causally (flash kernel's bottom-right
    alignment, ops/flash_attention.py); ``decode`` is the incremental
    single-token step used by the pipelined decoder.

    ``num_kv_heads`` enables grouped-query attention (MQA at 1): query
    heads share ``num_heads // num_kv_heads``-way KV groups, shrinking the
    decode KV cache — and its per-step HBM read, the decode bottleneck —
    by that factor.  ``None`` keeps classic multi-head attention.
    """

    num_kv_heads: int | None = None

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads

    def _check_kv(self):
        if self.num_heads % self.kv_heads:
            raise ValueError(
                f"num_heads={self.num_heads} not divisible by "
                f"num_kv_heads={self.kv_heads}")

    def init(self, key, in_specs):
        kv = self.kv_heads
        if kv == self.num_heads:
            return super().init(key, in_specs)
        self._check_kv()
        (spec,) = in_specs
        d = spec.shape[-1]
        hd = d // self.num_heads
        p = super().init(key, in_specs)
        # narrow the fused qkv projection: d query cols + 2*kv*hd KV cols
        w = p["qkv"]["w"]
        p["qkv"] = {
            "w": jnp.concatenate(
                [w[:, :d], w[:, d: d + kv * hd],
                 w[:, 2 * d: 2 * d + kv * hd]], axis=-1),
            "b": jnp.zeros((d + 2 * kv * hd,), jnp.float32),
        }
        return p

    def _split_qkv(self, qkv):
        """Static q/k/v column split: d query cols, kv*hd each for K/V."""
        nh, kv = self.num_heads, self.kv_heads
        hd = qkv.shape[-1] // (nh + 2 * kv)
        dq = nh * hd
        return (qkv[..., :dq], qkv[..., dq: dq + kv * hd],
                qkv[..., dq + kv * hd:])

    def _kv_head_count(self) -> int:
        return self.kv_heads

    def flops(self, in_specs, out_spec):
        # base formula assumes a 3d-wide qkv projection; GQA narrows it
        (spec,) = in_specs
        t, d = spec.shape
        qkv_cols = d + 2 * self.kv_heads * (d // self.num_heads)
        return (2 * t * d * (qkv_cols + d + 2 * self.mlp_ratio * d)
                + 4 * t * t * d)

    def _attend(self, q, k, v):
        impl = self.attn_impl
        if impl == "auto":
            impl = "flash" if jax.default_backend() == "tpu" else "xla"
        if impl not in ("flash", "xla"):
            raise ValueError(
                f"attn_impl must be 'auto', 'flash' or 'xla', got {impl!r}")
        if impl == "flash":
            from ..ops import flash_attention
            return flash_attention(q, k, v, causal=True)
        hd = q.shape[-1]
        t_q, t_k = q.shape[2], k.shape[2]
        att = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(hd)
        q_pos = jnp.arange(t_q)[:, None] + (t_k - t_q)
        mask = q_pos >= jnp.arange(t_k)[None, :]
        att = jnp.where(mask, att, jnp.asarray(-jnp.inf, att.dtype))
        att = jax.nn.softmax(att, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", att, v)

    # apply/apply_with_kv are inherited: the base TransformerBlock forward
    # (graph/ops.py) is the single implementation, made causal here purely
    # through the _attend override above.  apply_with_kv's K/V columns
    # match what decode() writes row-by-row (pre-head-split qkv
    # projections), so pipelined prefill bulk-writes cache rows 0..t-1
    # (after the head-major relayout) and decoding continues at t.

    @staticmethod
    def quantize_row(row):
        """Symmetric per-(head, position)-row int8: [..., hd] float ->
        ([..., hd] int8, [...] f32 scale).  One scale per cache row keeps
        dequantization a scalar multiply that folds EXACTLY into the
        attention contractions (the scale is constant over the contracted
        head dim), so the int8 cache is read raw by the dots and no
        dequantized copy is ever materialized."""
        rowf = row.astype(jnp.float32)
        amax = jnp.max(jnp.abs(rowf), axis=-1)
        scale = jnp.where(amax > 0, amax / 127.0, 1.0)
        q = jnp.clip(jnp.round(rowf / scale[..., None]), -127, 127)
        return q.astype(jnp.int8), scale

    def decode(self, params, x, k_cache, v_cache, pos,
               k_scale=None, v_scale=None):
        """One-token step: ``x`` [b, d] at position ``pos``.

        ``k_cache``/``v_cache`` are **head-major** [b, kv, L, hd] with
        L > max position — KV heads lead so the attention contractions are
        plain batched dots; a position-major [b, L, d] layout would make
        XLA materialize a transpose of the whole cache every step.  Under
        GQA, kv < num_heads and each cache head serves its whole query
        group without materializing repeats.  The new key/value row is
        written at ``pos`` (callers pass a clamped scratch index for
        bubble steps) and attention covers positions <= ``pos``.

        With ``k_scale``/``v_scale`` ([b, kv, L] f32) the caches are int8
        rows quantized by :meth:`quantize_row`; scales fold into the dots
        exactly (per-row constants), so ICI^W HBM reads shrink to ~1
        byte/value.  Returns ``(y, k_cache, v_cache)`` plus the updated
        scales when quantized.
        """
        p = _cast(params, x.dtype)
        b, d = x.shape
        nh = self.num_heads
        kv = self.kv_heads
        grp = nh // kv
        hd = d // nh
        cache_len = k_cache.shape[2]
        quant = k_scale is not None

        y = self._ln(p["ln1"], x, self.ln_eps)
        qkv = y @ p["qkv"]["w"] + p["qkv"]["b"]
        q, k_new, v_new = self._split_qkv(qkv)
        k_row = k_new.reshape(b, kv, 1, hd)
        v_row = v_new.reshape(b, kv, 1, hd)
        if quant:
            k_row, ks_row = self.quantize_row(k_row)
            v_row, vs_row = self.quantize_row(v_row)
            k_scale = lax.dynamic_update_slice(k_scale, ks_row, (0, 0, pos))
            v_scale = lax.dynamic_update_slice(v_scale, vs_row, (0, 0, pos))
        k_cache = lax.dynamic_update_slice(
            k_cache, k_row.astype(k_cache.dtype), (0, 0, pos, 0))
        v_cache = lax.dynamic_update_slice(
            v_cache, v_row.astype(v_cache.dtype), (0, 0, pos, 0))

        qh = q.reshape(b, kv, grp, hd)
        kh = k_cache.astype(x.dtype)
        vh = v_cache.astype(x.dtype)
        att = jnp.einsum("bkgd,bkld->bkgl", qh, kh) / math.sqrt(hd)
        if quant:
            att = att * k_scale[:, :, None, :].astype(att.dtype)
        live = jnp.arange(cache_len)[None, None, None, :] <= pos
        att = jnp.where(live, att, jnp.asarray(-jnp.inf, att.dtype))
        att = jax.nn.softmax(att, axis=-1)
        if quant:
            att = att * v_scale[:, :, None, :].astype(att.dtype)
        y = jnp.einsum("bkgl,bkld->bkgd", att, vh).reshape(b, d)
        x = x + (y @ p["proj"]["w"] + p["proj"]["b"])

        y = self._ln(p["ln2"], x, self.ln_eps)
        y = jax.nn.gelu(y @ p["fc1"]["w"] + p["fc1"]["b"])
        out = x + (y @ p["fc2"]["w"] + p["fc2"]["b"])
        if quant:
            return out, k_cache, v_cache, k_scale, v_scale
        return out, k_cache, v_cache


class GptEmbedding(Op):
    """Token + learned positional embeddings (GPT-2 style, no post-LN)."""

    def __init__(self, vocab: int, features: int, max_len: int):
        self.vocab = vocab
        self.features = features
        self.max_len = max_len

    def init(self, key, in_specs):
        del in_specs
        k1, k2 = jax.random.split(key)
        return {
            "wte": jax.random.normal(k1, (self.vocab, self.features),
                                     jnp.float32) * 0.02,
            "wpe": jax.random.normal(k2, (self.max_len, self.features),
                                     jnp.float32) * 0.01,
        }

    def apply(self, params, ids):
        t = ids.shape[1]
        return (params["wte"][ids.astype(jnp.int32)]
                + params["wpe"][:t])

    def embed_at(self, params, ids, pos):
        """Decode-path embedding: ``ids`` [b] at scalar position ``pos``."""
        tok = params["wte"][ids.astype(jnp.int32)]
        return tok + lax.dynamic_slice(params["wpe"], (pos, 0),
                                       (1, self.features))[0]

    def flops(self, in_specs, out_spec):
        return out_spec.size


def gpt(num_layers: int, hidden: int, heads: int, seq_len: int,
        vocab: int = 50257, kv_heads: int | None = None,
        ln_eps: float = 1e-6, name: str = "gpt") -> LayerGraph:
    """Causal LM graph: ids [t] -> logits [t, vocab].

    ``block_k`` nodes are the pipeline cut points; the decode engine
    (:mod:`defer_tpu.runtime.decode`) consumes the same graph by node-name
    contract: ``embeddings``, ``block_0..``, ``final_ln``, ``lm_head``.
    ``kv_heads`` < ``heads`` builds a GQA model (MQA at 1).  ``ln_eps``
    is threaded through every block and the final LayerNorm — HF GPT-2
    checkpoints were trained at 1e-5 (see :func:`gpt2_small`).
    """
    b = GraphBuilder(name)
    x = b.input((seq_len,), jnp.int32)
    x = b.add(GptEmbedding(vocab, hidden, seq_len), x, name="embeddings")
    for i in range(num_layers):
        x = b.add(CausalTransformerBlock(heads, num_kv_heads=kv_heads,
                                         ln_eps=ln_eps),
                  x, name=f"block_{i}")
    x = b.add(LayerNorm(eps=ln_eps), x, name="final_ln")
    x = b.add(Dense(vocab), x, name="lm_head")
    return b.build()


def gpt_small(seq_len: int = 256, kv_heads: int | None = None) -> LayerGraph:
    """GPT-2 small geometry (12 layers, d=768, 12 heads)."""
    return gpt(12, 768, 12, seq_len, kv_heads=kv_heads, name="gpt_small")


def gpt2_small(seq_len: int = 256) -> LayerGraph:
    """HF-faithful GPT-2 small: same geometry as :func:`gpt_small` but
    with GPT-2's trained LN epsilon (1e-5), so ``gpt2`` checkpoints
    (``utils/pretrained.py: load_pretrained_gpt2``) reproduce HF logits.
    """
    return gpt(12, 768, 12, seq_len, ln_eps=1e-5, name="gpt2_small")


def gpt_tiny(seq_len: int = 16, vocab: int = 97,
             kv_heads: int | None = None) -> LayerGraph:
    return gpt(4, 32, 2, seq_len, vocab=vocab, kv_heads=kv_heads,
               name="gpt_tiny")


def gpt_stage_cuts(num_layers: int, num_stages: int) -> list[str]:
    """Even block-boundary cut points for an ``num_stages``-stage pipeline."""
    if not 1 <= num_stages <= num_layers:
        raise ValueError(f"need 1 <= stages <= {num_layers}")
    per = num_layers / num_stages
    return [f"block_{round(per * (s + 1)) - 1}"
            for s in range(num_stages - 1)]
