"""Inception-v3-style branching model (BASELINE.md config 3: 6 partitions —
the branching-DAG stress test for the partitioner).

Inside each inception block, four parallel branches (1x1 / 5x5 / double-3x3 /
pool-proj) diverge and re-join at a channel Concat — so nothing inside a
block is a valid cut point and the articulation analysis must only offer the
block-boundary ``mixed_k`` concat nodes (plus the sequential stem).  This is
exactly the property the reference silently depends on when it cuts ResNet50
only at ``add_*`` layers (reference test/test.py:18, src/dag_util.py:28).

The block structure follows the standard Inception-v3 shape (stem, 3x A
blocks, grid reduction, 4x B blocks, reduction, 2x C blocks); channel counts
are parameterizable so tests can run a scaled-down variant.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..graph.ir import GraphBuilder, LayerGraph
from ..graph.ops import (Activation, AvgPool, BatchNorm, Concat, Conv2D,
                         Dense, GlobalAvgPool, MaxPool)


def _cbr(b: GraphBuilder, x: str, feats: int, kernel, stride=1,
         padding="SAME", eps=1e-5) -> str:
    x = b.add(Conv2D(feats, kernel, stride, padding, use_bias=False), x)
    x = b.add(BatchNorm(eps=eps), x)
    return b.add(Activation("relu"), x)


def _block_a(b: GraphBuilder, x: str, f: int, pool_f: int, idx: int) -> str:
    b1 = _cbr(b, x, f, 1)
    b2 = _cbr(b, _cbr(b, x, f * 3 // 4, 1), f, 5)
    b3 = _cbr(b, _cbr(b, _cbr(b, x, f, 1), f * 3 // 2, 3), f * 3 // 2, 3)
    b4 = _cbr(b, b.add(AvgPool(3, 1, "SAME"), x), pool_f, 1)
    return b.add(Concat(), [b1, b2, b3, b4], name=f"mixed_{idx}")


def _reduction(b: GraphBuilder, x: str, f: int, idx: int) -> str:
    b1 = _cbr(b, x, f * 2, 3, stride=2, padding="VALID")
    b2 = _cbr(b, _cbr(b, _cbr(b, x, f, 1), f, 3), f, 3, stride=2,
              padding="VALID")
    b3 = b.add(MaxPool(3, 2, "VALID"), x)
    return b.add(Concat(), [b1, b2, b3], name=f"mixed_{idx}")


def _block_b(b: GraphBuilder, x: str, f: int, out_f: int, idx: int) -> str:
    b1 = _cbr(b, x, out_f, 1)
    b2 = _cbr(b, _cbr(b, _cbr(b, x, f, 1), f, (1, 7)), out_f, (7, 1))
    b3 = _cbr(b, _cbr(b, _cbr(b, _cbr(b, _cbr(
        b, x, f, 1), f, (7, 1)), f, (1, 7)), f, (7, 1)), out_f, (1, 7))
    b4 = _cbr(b, b.add(AvgPool(3, 1, "SAME"), x), out_f, 1)
    return b.add(Concat(), [b1, b2, b3, b4], name=f"mixed_{idx}")


def _block_c(b: GraphBuilder, x: str, f: int, idx: int) -> str:
    b1 = _cbr(b, x, f, 1)
    mid2 = _cbr(b, x, f, 1)
    b2 = b.add(Concat(), [_cbr(b, mid2, f, (1, 3)), _cbr(b, mid2, f, (3, 1))])
    mid3 = _cbr(b, _cbr(b, x, f * 3 // 2, 1), f, 3)
    b3 = b.add(Concat(), [_cbr(b, mid3, f, (1, 3)), _cbr(b, mid3, f, (3, 1))])
    b4 = _cbr(b, b.add(AvgPool(3, 1, "SAME"), x), f // 2, 1)
    return b.add(Concat(), [b1, b2, b3, b4], name=f"mixed_{idx}")


def inception(width: int = 64, num_classes: int = 1000,
              image_size: int = 299, name: str = "inception") -> LayerGraph:
    w = width
    b = GraphBuilder(name)
    x = b.input((image_size, image_size, 3), jnp.float32)
    # stem
    x = _cbr(b, x, w // 2, 3, stride=2, padding="VALID")
    x = _cbr(b, x, w // 2, 3, padding="VALID")
    x = _cbr(b, x, w, 3)
    x = b.add(MaxPool(3, 2, "VALID"), x, name="stem_pool")
    x = _cbr(b, x, w * 5 // 4, 1)
    x = _cbr(b, x, w * 3, 3, padding="VALID")
    x = b.add(MaxPool(3, 2, "VALID"), x, name="stem_pool2")
    # inception stacks
    idx = 0
    for _ in range(3):
        x = _block_a(b, x, w, w // 2, idx)
        idx += 1
    x = _reduction(b, x, w * 3, idx)
    idx += 1
    for _ in range(4):
        x = _block_b(b, x, w * 2, w * 3, idx)
        idx += 1
    x = _reduction(b, x, w * 3, idx)
    idx += 1
    for _ in range(2):
        x = _block_c(b, x, w * 6, idx)
        idx += 1
    x = b.add(GlobalAvgPool(), x, name="avg_pool")
    x = b.add(Dense(num_classes), x, name="predictions")
    return b.build()


def _tcbr(b: GraphBuilder, x: str, feats: int, kernel, stride=1,
          padding="SAME") -> str:
    """torchvision ``BasicConv2d``: conv (no bias) + BN(eps=1e-3) + relu.

    All stride-2 convs in InceptionV3 are pad-0 (= VALID, identical in
    torch and XLA); all stride-1 convs pad symmetrically to k//2 per side
    (= SAME at stride 1), so no explicit padding tuples are needed —
    unlike the torch-trained ResNet/MobileNet imports.
    """
    return _cbr(b, x, feats, kernel, stride, padding, eps=1e-3)


def _tpool_branch(b: GraphBuilder, x: str, feats: int) -> str:
    # torch F.avg_pool2d(x, 3, stride=1, padding=1): count_include_pad
    pool = b.add(AvgPool(3, 1, "SAME", count_include_pad=True), x)
    return _tcbr(b, pool, feats, 1)


def _t_block_a(b: GraphBuilder, x: str, pool_feats: int, idx: int) -> str:
    b1 = _tcbr(b, x, 64, 1)
    b5 = _tcbr(b, _tcbr(b, x, 48, 1), 64, 5)
    bd = _tcbr(b, _tcbr(b, _tcbr(b, x, 64, 1), 96, 3), 96, 3)
    bp = _tpool_branch(b, x, pool_feats)
    return b.add(Concat(), [b1, b5, bd, bp], name=f"mixed_{idx}")


def _t_block_b(b: GraphBuilder, x: str, idx: int) -> str:
    b3 = _tcbr(b, x, 384, 3, stride=2, padding="VALID")
    bd = _tcbr(b, _tcbr(b, _tcbr(b, x, 64, 1), 96, 3), 96, 3, stride=2,
               padding="VALID")
    bp = b.add(MaxPool(3, 2, "VALID"), x)
    return b.add(Concat(), [b3, bd, bp], name=f"mixed_{idx}")


def _t_block_c(b: GraphBuilder, x: str, c7: int, idx: int) -> str:
    b1 = _tcbr(b, x, 192, 1)
    b7 = _tcbr(b, _tcbr(b, _tcbr(b, x, c7, 1), c7, (1, 7)), 192, (7, 1))
    bd = _tcbr(b, _tcbr(b, _tcbr(b, _tcbr(b, _tcbr(
        b, x, c7, 1), c7, (7, 1)), c7, (1, 7)), c7, (7, 1)), 192, (1, 7))
    bp = _tpool_branch(b, x, 192)
    return b.add(Concat(), [b1, b7, bd, bp], name=f"mixed_{idx}")


def _t_block_d(b: GraphBuilder, x: str, idx: int) -> str:
    b3 = _tcbr(b, _tcbr(b, x, 192, 1), 320, 3, stride=2, padding="VALID")
    b7 = _tcbr(b, _tcbr(b, _tcbr(b, _tcbr(
        b, x, 192, 1), 192, (1, 7)), 192, (7, 1)), 192, 3, stride=2,
        padding="VALID")
    bp = b.add(MaxPool(3, 2, "VALID"), x)
    return b.add(Concat(), [b3, b7, bp], name=f"mixed_{idx}")


def _t_block_e(b: GraphBuilder, x: str, idx: int) -> str:
    b1 = _tcbr(b, x, 320, 1)
    m3 = _tcbr(b, x, 384, 1)
    b3 = b.add(Concat(),
               [_tcbr(b, m3, 384, (1, 3)), _tcbr(b, m3, 384, (3, 1))])
    md = _tcbr(b, _tcbr(b, x, 448, 1), 384, 3)
    bd = b.add(Concat(),
               [_tcbr(b, md, 384, (1, 3)), _tcbr(b, md, 384, (3, 1))])
    bp = _tpool_branch(b, x, 192)
    return b.add(Concat(), [b1, b3, bd, bp], name=f"mixed_{idx}")


def inception_v3(num_classes: int = 1000, image_size: int = 299) -> LayerGraph:
    """Exact torchvision InceptionV3 (eval semantics, no aux head).

    Block-for-block and channel-for-channel the torchvision module tree —
    Conv2d_1a..4a stem, Mixed_5b/5c/5d (A, pool 32/64/64), Mixed_6a (B),
    Mixed_6b..6e (C, c7 128/160/160/192), Mixed_7a (D), Mixed_7b/7c (E) —
    named ``mixed_0..mixed_10`` here, so torchvision checkpoints import
    weight-for-weight (``utils/pretrained.py: inception_v3_torch_mapping``)
    and the benchmark config measures the real InceptionV3 FLOPs.  BN eps
    is 1e-3 and the pool branches divide by 9 at the borders
    (``count_include_pad``), both matching torch.  The aux classifier and
    train-time dropout do not exist in the inference graph; torchvision's
    ``transform_input`` re-normalization is a preprocessing concern (feed
    TF-style ``(x-0.5)/0.5`` inputs, or apply the affine before ingest).
    """
    b = GraphBuilder("inception_v3")
    x = b.input((image_size, image_size, 3), jnp.float32)
    x = _tcbr(b, x, 32, 3, stride=2, padding="VALID")   # Conv2d_1a_3x3
    x = _tcbr(b, x, 32, 3, padding="VALID")             # Conv2d_2a_3x3
    x = _tcbr(b, x, 64, 3)                              # Conv2d_2b_3x3
    x = b.add(MaxPool(3, 2, "VALID"), x, name="stem_pool")
    x = _tcbr(b, x, 80, 1, padding="VALID")             # Conv2d_3b_1x1
    x = _tcbr(b, x, 192, 3, padding="VALID")            # Conv2d_4a_3x3
    x = b.add(MaxPool(3, 2, "VALID"), x, name="stem_pool2")
    idx = 0
    for pool_feats in (32, 64, 64):                     # Mixed_5b/5c/5d
        x = _t_block_a(b, x, pool_feats, idx)
        idx += 1
    x = _t_block_b(b, x, idx)                           # Mixed_6a
    idx += 1
    for c7 in (128, 160, 160, 192):                     # Mixed_6b..6e
        x = _t_block_c(b, x, c7, idx)
        idx += 1
    x = _t_block_d(b, x, idx)                           # Mixed_7a
    idx += 1
    for _ in range(2):                                  # Mixed_7b/7c
        x = _t_block_e(b, x, idx)
        idx += 1
    x = b.add(GlobalAvgPool(), x, name="avg_pool")
    x = b.add(Dense(num_classes), x, name="predictions")
    return b.build()


def inception_tiny(num_classes: int = 10, image_size: int = 75) -> LayerGraph:
    return inception(8, num_classes, image_size, name="inception_tiny")


#: 6-stage cuts at block boundaries (BASELINE.md config 3)
INCEPTION_6STAGE_CUTS = ["mixed_0", "mixed_2", "mixed_4", "mixed_6",
                         "mixed_8"]
