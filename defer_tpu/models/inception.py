"""Inception-v3-style branching model (BASELINE.md config 3: 6 partitions —
the branching-DAG stress test for the partitioner).

Inside each inception block, four parallel branches (1x1 / 5x5 / double-3x3 /
pool-proj) diverge and re-join at a channel Concat — so nothing inside a
block is a valid cut point and the articulation analysis must only offer the
block-boundary ``mixed_k`` concat nodes (plus the sequential stem).  This is
exactly the property the reference silently depends on when it cuts ResNet50
only at ``add_*`` layers (reference test/test.py:18, src/dag_util.py:28).

The block structure follows the standard Inception-v3 shape (stem, 3x A
blocks, grid reduction, 4x B blocks, reduction, 2x C blocks); channel counts
are parameterizable so tests can run a scaled-down variant.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..graph.ir import GraphBuilder, LayerGraph
from ..graph.ops import (Activation, AvgPool, BatchNorm, Concat, Conv2D,
                         Dense, GlobalAvgPool, MaxPool)


def _cbr(b: GraphBuilder, x: str, feats: int, kernel, stride=1,
         padding="SAME") -> str:
    x = b.add(Conv2D(feats, kernel, stride, padding, use_bias=False), x)
    x = b.add(BatchNorm(), x)
    return b.add(Activation("relu"), x)


def _block_a(b: GraphBuilder, x: str, f: int, pool_f: int, idx: int) -> str:
    b1 = _cbr(b, x, f, 1)
    b2 = _cbr(b, _cbr(b, x, f * 3 // 4, 1), f, 5)
    b3 = _cbr(b, _cbr(b, _cbr(b, x, f, 1), f * 3 // 2, 3), f * 3 // 2, 3)
    b4 = _cbr(b, b.add(AvgPool(3, 1, "SAME"), x), pool_f, 1)
    return b.add(Concat(), [b1, b2, b3, b4], name=f"mixed_{idx}")


def _reduction(b: GraphBuilder, x: str, f: int, idx: int) -> str:
    b1 = _cbr(b, x, f * 2, 3, stride=2, padding="VALID")
    b2 = _cbr(b, _cbr(b, _cbr(b, x, f, 1), f, 3), f, 3, stride=2,
              padding="VALID")
    b3 = b.add(MaxPool(3, 2, "VALID"), x)
    return b.add(Concat(), [b1, b2, b3], name=f"mixed_{idx}")


def _block_b(b: GraphBuilder, x: str, f: int, out_f: int, idx: int) -> str:
    b1 = _cbr(b, x, out_f, 1)
    b2 = _cbr(b, _cbr(b, _cbr(b, x, f, 1), f, (1, 7)), out_f, (7, 1))
    b3 = _cbr(b, _cbr(b, _cbr(b, _cbr(b, _cbr(
        b, x, f, 1), f, (7, 1)), f, (1, 7)), f, (7, 1)), out_f, (1, 7))
    b4 = _cbr(b, b.add(AvgPool(3, 1, "SAME"), x), out_f, 1)
    return b.add(Concat(), [b1, b2, b3, b4], name=f"mixed_{idx}")


def _block_c(b: GraphBuilder, x: str, f: int, idx: int) -> str:
    b1 = _cbr(b, x, f, 1)
    mid2 = _cbr(b, x, f, 1)
    b2 = b.add(Concat(), [_cbr(b, mid2, f, (1, 3)), _cbr(b, mid2, f, (3, 1))])
    mid3 = _cbr(b, _cbr(b, x, f * 3 // 2, 1), f, 3)
    b3 = b.add(Concat(), [_cbr(b, mid3, f, (1, 3)), _cbr(b, mid3, f, (3, 1))])
    b4 = _cbr(b, b.add(AvgPool(3, 1, "SAME"), x), f // 2, 1)
    return b.add(Concat(), [b1, b2, b3, b4], name=f"mixed_{idx}")


def inception(width: int = 64, num_classes: int = 1000,
              image_size: int = 299, name: str = "inception") -> LayerGraph:
    w = width
    b = GraphBuilder(name)
    x = b.input((image_size, image_size, 3), jnp.float32)
    # stem
    x = _cbr(b, x, w // 2, 3, stride=2, padding="VALID")
    x = _cbr(b, x, w // 2, 3, padding="VALID")
    x = _cbr(b, x, w, 3)
    x = b.add(MaxPool(3, 2, "VALID"), x, name="stem_pool")
    x = _cbr(b, x, w * 5 // 4, 1)
    x = _cbr(b, x, w * 3, 3, padding="VALID")
    x = b.add(MaxPool(3, 2, "VALID"), x, name="stem_pool2")
    # inception stacks
    idx = 0
    for _ in range(3):
        x = _block_a(b, x, w, w // 2, idx)
        idx += 1
    x = _reduction(b, x, w * 3, idx)
    idx += 1
    for _ in range(4):
        x = _block_b(b, x, w * 2, w * 3, idx)
        idx += 1
    x = _reduction(b, x, w * 3, idx)
    idx += 1
    for _ in range(2):
        x = _block_c(b, x, w * 6, idx)
        idx += 1
    x = b.add(GlobalAvgPool(), x, name="avg_pool")
    x = b.add(Dense(num_classes), x, name="predictions")
    return b.build()


def inception_v3(num_classes: int = 1000, image_size: int = 299) -> LayerGraph:
    return inception(64, num_classes, image_size, name="inception_v3")


def inception_tiny(num_classes: int = 10, image_size: int = 75) -> LayerGraph:
    return inception(8, num_classes, image_size, name="inception_tiny")


#: 6-stage cuts at block boundaries (BASELINE.md config 3)
INCEPTION_6STAGE_CUTS = ["mixed_0", "mixed_2", "mixed_4", "mixed_6",
                         "mixed_8"]
