"""MobileNetV2 (BASELINE.md config 4: 2 partitions — small model, the
communication-bound regime where per-hop transfer cost matters most relative
to per-stage compute).

Inverted-residual bottlenecks with depthwise convs and ReLU6; residual adds
are named ``add_k`` so block boundaries are the natural cut points, mirroring
the reference's ResNet cut convention (reference test/test.py:18).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..graph.ir import GraphBuilder, LayerGraph
from ..graph.ops import (Activation, Add, BatchNorm, Conv2D, Dense,
                         DepthwiseConv2D, GlobalAvgPool)


def _cbr6(b, x, feats, kernel, stride=1):
    # symmetric k//2 padding: torch's convention (== SAME at stride 1,
    # differs from XLA SAME at stride 2) so torchvision weights reproduce
    x = b.add(Conv2D(feats, kernel, stride, (kernel // 2, kernel // 2),
                     use_bias=False), x)
    x = b.add(BatchNorm(), x)
    return b.add(Activation("relu6"), x)


def _inverted_residual(b: GraphBuilder, x: str, in_ch: int, out_ch: int,
                       stride: int, expand: int, add_idx: list[int]) -> str:
    inp = x
    if expand != 1:
        x = _cbr6(b, x, in_ch * expand, 1)
    x = b.add(DepthwiseConv2D(3, stride, (1, 1)), x)
    x = b.add(BatchNorm(), x)
    x = b.add(Activation("relu6"), x)
    x = b.add(Conv2D(out_ch, 1, use_bias=False), x)
    x = b.add(BatchNorm(), x)
    if stride == 1 and in_ch == out_ch:
        name = "add" if add_idx[0] == 0 else f"add_{add_idx[0]}"
        x = b.add(Add(), [x, inp], name=name)
        add_idx[0] += 1
    return x


# (expand, out_channels, repeats, stride) per stage — standard V2 recipe
_V2_CFG = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
           (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]


def mobilenet_v2(num_classes: int = 1000, image_size: int = 224,
                 width_mult: float = 1.0,
                 name: str = "mobilenet_v2") -> LayerGraph:
    def c(ch):
        return max(8, int(ch * width_mult))

    b = GraphBuilder(name)
    x = b.input((image_size, image_size, 3), jnp.float32)
    x = _cbr6(b, x, c(32), 3, stride=2)
    in_ch = c(32)
    add_idx = [0]
    for expand, out, reps, stride in _V2_CFG:
        for i in range(reps):
            x = _inverted_residual(b, x, in_ch, c(out),
                                   stride if i == 0 else 1, expand, add_idx)
            in_ch = c(out)
    x = _cbr6(b, x, c(1280), 1)
    x = b.add(GlobalAvgPool(), x, name="avg_pool")
    x = b.add(Dense(num_classes), x, name="predictions")
    return b.build()


def mobilenet_tiny(num_classes: int = 10, image_size: int = 32) -> LayerGraph:
    return mobilenet_v2(num_classes, image_size, width_mult=0.25,
                        name="mobilenet_tiny")


#: the 2-stage comm-bound config (BASELINE.md config 4): cut mid-network
MOBILENETV2_2STAGE_CUTS = ["add_3"]
