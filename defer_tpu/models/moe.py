"""Mixture-of-experts transformer family (6th model family, beyond the
five BASELINE.md configs).

Alternates dense attention blocks (``TransformerBlock``) with switch-MoE
FFN layers (``ops.MoE``); every block output is a single-tensor cut point,
so the family pipelines exactly like BERT.  Inside a pipeline stage the MoE
op runs its dense (evaluate-all-experts, mask) form; the expert-parallel
all_to_all execution over an "expert" mesh axis is available standalone via
:mod:`defer_tpu.parallel.expert`.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..graph.ir import GraphBuilder, LayerGraph
from ..graph.ops import Add, ExpertBranch, LayerNorm, MoE, TransformerBlock
from .bert import BertEmbedding, Pooler


def moe_transformer(num_layers: int, hidden: int, heads: int,
                    num_experts: int, expert_hidden: int, seq_len: int,
                    vocab: int = 30522,
                    name: str = "moe_transformer") -> LayerGraph:
    b = GraphBuilder(name)
    x = b.input((seq_len,), jnp.int32)
    x = b.add(BertEmbedding(vocab, hidden, seq_len), x, name="embeddings")
    for i in range(num_layers):
        x = b.add(TransformerBlock(heads), x, name=f"block_{i}")
        x = b.add(MoE(num_experts, expert_hidden), x, name=f"moe_{i}")
    x = b.add(LayerNorm(), x, name="final_ln")
    x = b.add(Pooler(hidden), x, name="pooler")
    return b.build()


def moe_tiny(seq_len: int = 16) -> LayerGraph:
    return moe_transformer(2, 32, 2, 4, 64, seq_len, vocab=100,
                           name="moe_tiny")


#: one (attention block + MoE) pair per stage
def moe_stage_cuts(num_layers: int) -> list[str]:
    return [f"moe_{i}" for i in range(num_layers - 1)]


def moe_branched(num_layers: int, hidden: int, heads: int,
                 num_experts: int, expert_hidden: int, seq_len: int,
                 vocab: int = 30522,
                 name: str = "moe_branched") -> LayerGraph:
    """Expert-parallel-shaped MoE: each expert is its own GRAPH branch.

    The fused :class:`~defer_tpu.graph.ops.MoE` op above evaluates every
    expert inside one node, so a pipeline cut can never separate them —
    expert parallelism is forced through the SPMD path
    (``parallel/expert.py``).  This variant expands each MoE layer into
    a fork/join region the DAG planner can see: the attention block's
    output forks to ``num_experts`` :class:`ExpertBranch` nodes (each
    one expert's gate-weighted FFN) plus a residual skip, joined by an
    ``Add`` — soft-mixture semantics, one expert of compute per branch.
    Every region is exactly the branch structure
    ``graph.analysis.branch_regions`` detects, which makes this family
    the MoE scenario for branch-parallel serving (docs/PLANNER.md).
    """
    b = GraphBuilder(name)
    x = b.input((seq_len,), jnp.int32)
    x = b.add(BertEmbedding(vocab, hidden, seq_len), x, name="embeddings")
    for i in range(num_layers):
        x = b.add(TransformerBlock(heads), x, name=f"block_{i}")
        experts = [
            b.add(ExpertBranch(num_experts, e, expert_hidden), x,
                  name=f"moe_{i}_e{e}")
            for e in range(num_experts)]
        # residual skip first: branch 0 of the region is the empty
        # (direct fork->join) path, experts are paths 1..E
        x = b.add(Add(), [x] + experts, name=f"moe_{i}")
    x = b.add(LayerNorm(), x, name="final_ln")
    x = b.add(Pooler(hidden), x, name="pooler")
    return b.build()


def moe_branched_tiny(seq_len: int = 16) -> LayerGraph:
    return moe_branched(2, 32, 2, 4, 64, seq_len, vocab=100,
                        name="moe_branched_tiny")
