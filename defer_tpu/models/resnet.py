"""ResNet family on the layer-graph IR (NHWC, inference-mode BN).

ResNet50 is the reference's flagship benchmark: 8 partitions cut at the
residual-add articulation layers ``add_2, add_4, ..., add_14`` (reference
test/test.py:14-18).  The graph here names its residual merges ``add_k`` in
the same convention, so the reference's exact cut list is valid verbatim.

``resnet_tiny`` is a scaled-down variant for fast CPU-mesh tests.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..graph.ir import GraphBuilder, LayerGraph
from ..graph.ops import (Activation, Add, BatchNorm, Conv2D, Dense,
                         GlobalAvgPool, MaxPool)


def _conv_bn(b: GraphBuilder, x: str, features: int, kernel: int,
             stride: int = 1, relu: bool = True) -> str:
    # explicit symmetric k//2 padding == SAME at stride 1, and matches
    # torch's convention at stride 2 (where XLA SAME pads asymmetrically)
    # so torchvision-trained weights reproduce bit-comparable activations
    pad = (kernel // 2, kernel // 2)
    x = b.add(Conv2D(features, kernel, stride, pad, use_bias=False), x)
    x = b.add(BatchNorm(), x)
    if relu:
        x = b.add(Activation("relu"), x)
    return x


def _bottleneck(b: GraphBuilder, x: str, features: int, stride: int,
                project: bool, add_idx: int) -> str:
    """Post-activation bottleneck block ending in a named ``add_k`` node.

    Stride lives on the 3x3 conv (ResNet v1.5) — torchvision's layout, so
    its checkpoints import with matching semantics, not just shapes.
    """
    shortcut = x
    if project:
        shortcut = _conv_bn(b, x, 4 * features, 1, stride, relu=False)
    y = _conv_bn(b, x, features, 1, 1)
    y = _conv_bn(b, y, features, 3, stride)
    y = _conv_bn(b, y, 4 * features, 1, 1, relu=False)
    name = "add" if add_idx == 0 else f"add_{add_idx}"
    out = b.add(Add(), [y, shortcut], name=name)
    return b.add(Activation("relu"), out)


def resnet(depths: list[int], width: int = 64, num_classes: int = 1000,
           image_size: int = 224, name: str = "resnet") -> LayerGraph:
    b = GraphBuilder(name)
    x = b.input((image_size, image_size, 3), jnp.float32)
    x = _conv_bn(b, x, width, 7, 2)
    x = b.add(MaxPool(3, 2, padding=(1, 1)), x)
    add_idx = 0
    for s, blocks in enumerate(depths):
        feats = width * (2 ** s)
        for i in range(blocks):
            stride = 2 if (s > 0 and i == 0) else 1
            x = _bottleneck(b, x, feats, stride, project=(i == 0), add_idx=add_idx)
            add_idx += 1
    x = b.add(GlobalAvgPool(), x)
    x = b.add(Dense(num_classes), x, name="predictions")
    return b.build()


def resnet50(num_classes: int = 1000, image_size: int = 224) -> LayerGraph:
    return resnet([3, 4, 6, 3], 64, num_classes, image_size, "resnet50")


def resnet_tiny(num_classes: int = 10, image_size: int = 32,
                width: int = 8) -> LayerGraph:
    """4 residual blocks / 8 valid add-cuts worth of structure, CPU-test fast."""
    return resnet([2, 2], width, num_classes, image_size, "resnet_tiny")


#: the reference benchmark's exact 8-stage cut list (test/test.py:18)
RESNET50_8STAGE_CUTS = ["add_2", "add_4", "add_6", "add_8", "add_10",
                        "add_12", "add_14"]
