"""VGG family (BASELINE.md config 2: VGG19, 4 partitions — deep sequential
model with large early activations, the stress test for activation-buffer
sizing).

Purely sequential graph: every layer output is a valid cut point, so the
FLOP-balanced auto-partitioner has maximal freedom here.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..graph.ir import GraphBuilder, LayerGraph
from ..graph.ops import Activation, Conv2D, Dense, Flatten, MaxPool


def vgg(cfg: list[int | str], num_classes: int = 1000, image_size: int = 224,
        fc_width: int = 4096, name: str = "vgg") -> LayerGraph:
    b = GraphBuilder(name)
    x = b.input((image_size, image_size, 3), jnp.float32)
    block, conv_in_block = 1, 1
    for v in cfg:
        if v == "M":
            x = b.add(MaxPool(2, 2), x, name=f"pool{block}")
            block += 1
            conv_in_block = 1
        else:
            x = b.add(Conv2D(int(v), 3), x,
                      name=f"conv{block}_{conv_in_block}")
            x = b.add(Activation("relu"), x,
                      name=f"relu{block}_{conv_in_block}")
            conv_in_block += 1
    x = b.add(Flatten(), x, name="flatten")
    x = b.add(Dense(fc_width), x, name="fc1")
    x = b.add(Activation("relu"), x, name="fc1_relu")
    x = b.add(Dense(fc_width), x, name="fc2")
    x = b.add(Activation("relu"), x, name="fc2_relu")
    x = b.add(Dense(num_classes), x, name="predictions")
    return b.build()


VGG19_CFG = [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
             512, 512, 512, 512, "M", 512, 512, 512, 512, "M"]


def vgg19(num_classes: int = 1000, image_size: int = 224) -> LayerGraph:
    return vgg(VGG19_CFG, num_classes, image_size, name="vgg19")


def vgg_tiny(num_classes: int = 10, image_size: int = 32) -> LayerGraph:
    return vgg([8, "M", 16, "M", 16, "M"], num_classes, image_size,
               fc_width=32, name="vgg_tiny")


#: natural 4-stage cuts for VGG19 (BASELINE.md config 2): block boundaries
VGG19_4STAGE_CUTS = ["pool2", "pool3", "pool4"]
