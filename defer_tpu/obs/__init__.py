"""Observability: histograms, a metrics registry, and a span tracer.

The runtime's headline claims (throughput, per-node idle time) are
observability claims, yet the reference measures them with a stopwatch in
its test harness (reference test/test.py:25-37) and our own
``PipelineMetrics`` only held averages.  This package gives the runtime a
first-class, always-on-cheap telemetry layer:

* :class:`LatencyHistogram` — log-bucketed, mergeable, p50/p95/p99/max.
* :class:`MetricsRegistry` — process-wide named counters / gauges /
  histograms with a JSON snapshot and Prometheus-style text exposition.
* :class:`Tracer` — trace_id/span_id spans with parent links and
  monotonic timestamps, exportable as Chrome trace-event JSON (open the
  file at https://ui.perfetto.dev).

Cost contract: counters are plain int attributes, span recording is an
O(1) list append under the GIL, and a *disabled* tracer costs exactly one
predicate per instrumentation site.  See docs/OBSERVABILITY.md.
"""

from .histogram import LatencyHistogram
from .registry import REGISTRY, Counter, Gauge, MetricsRegistry, get_registry
from .trace import (Tracer, enable_tracing, export_chrome_trace,
                    new_span_id, tracer, trace_context)
from .events import (EVENT_KINDS, FlightRecorder, merge_events,
                     recorder, validate_event)
from .events import emit as emit_event
from .attrib import (DoorAttribution, RequestAttribution,
                     attribute_request, attribute_sampled)
from .cluster import (ClusterView, StragglerDetector, StragglerFlag,
                      align_clock, estimate_clock_offset,
                      expected_stage_ms)
from .capacity import (CapacityModel, DriftAuditor, DriftFlag,
                       achieved_mfu, stage_flops_bytes)
from .report import ObsReporter, start_prom_server
from .journal import (JOURNAL_VERSION, JournalSpiller, JournalWriter,
                      active_journal, read_journal,
                      read_process_journals, start_journal, stop_journal)
from .postmortem import (BUNDLE_VERSION, collect as collect_postmortem,
                         maybe_autopsy)
from .profile import (ENGINE_PHASES, NODE_PHASES, MemoryWatcher,
                      ProfileSession, RecompileWatcher,
                      device_memory_bytes, memory_watcher,
                      recompile_watcher)

__all__ = [
    "LatencyHistogram",
    "MetricsRegistry", "REGISTRY", "get_registry", "Counter", "Gauge",
    "Tracer", "tracer", "enable_tracing", "export_chrome_trace",
    "trace_context", "new_span_id",
    "FlightRecorder", "recorder", "emit_event", "merge_events",
    "validate_event", "EVENT_KINDS",
    "RequestAttribution", "attribute_request", "attribute_sampled",
    "DoorAttribution",
    "ClusterView", "StragglerDetector", "StragglerFlag",
    "estimate_clock_offset", "align_clock", "expected_stage_ms",
    "CapacityModel", "DriftAuditor", "DriftFlag", "achieved_mfu",
    "stage_flops_bytes",
    "ObsReporter", "start_prom_server",
    "JOURNAL_VERSION", "JournalWriter", "JournalSpiller",
    "start_journal", "stop_journal", "active_journal",
    "read_journal", "read_process_journals",
    "BUNDLE_VERSION", "collect_postmortem", "maybe_autopsy",
    "NODE_PHASES", "ENGINE_PHASES", "ProfileSession",
    "RecompileWatcher", "recompile_watcher",
    "MemoryWatcher", "memory_watcher", "device_memory_bytes",
]
