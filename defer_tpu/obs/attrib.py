"""Per-request latency attribution: fold a served request's spans into
named budget buckets that sum to its measured end-to-end latency.

The serving plane stamps every request with door-side spans
(``serve.admission_wait`` / ``serve.gather`` / ``serve.deliver`` /
``serve.request`` — ``serve/frontdoor.py``) and its frame rides the
chain under a wire seq whose per-stage spans (``stageK.infer``,
``stageK.host_sync``) the existing waterfall machinery records in every
stage process on one clock-aligned timeline.  This module is the fold:

:func:`attribute_request` telescopes those spans into the buckets of
docs/OBSERVABILITY.md —

* ``admission`` — admitted -> popped by the batch former (queue wait),
* ``gather`` — popped -> frame submitted (batch forming window),
* ``transport.hopK`` — stage K-1's compute end -> stage K's compute
  start (tx queue + encode + wire + decode + rx queue of that hop,
  labeled with the hop's negotiated tier when known),
* ``stageK`` — stage K's issue-to-materialize compute, host sync
  excluded,
* ``host_sync`` — the summed ``np.asarray`` materializations (zero on
  device-resident ici hops, by construction),
* ``transport.result`` — last compute end -> demux receipt (the result
  hop),
* ``result_edge`` — demux -> the client's bytes written.

Because the buckets tile the request's own timeline, their sum equals
the measured wall up to cross-process clock skew — the residual is
reported, and :meth:`RequestAttribution.ok` is the "sums to within
tolerance" acceptance predicate the smoke/bench assert.

:class:`DoorAttribution` is the always-on, trace-free sibling: the
front door feeds it four timestamps per delivered unit and it keeps
per-tenant bucket histograms (admission / gather / chain / result
edge) — the ``attribution`` block of the serve stats reply and the
``monitor --serve --json`` lines.
"""

from __future__ import annotations

import re
import threading

from .histogram import LatencyHistogram

#: ``stage7.infer`` / ``stage7.host_sync`` (serving rides linear
#: chains, so no replica/branch infixes appear on the request path)
_STAGE_RE = re.compile(r"^stage(\d+)\.(infer|host_sync)$")


class RequestAttribution:
    """One request's folded budget buckets."""

    __slots__ = ("rid", "tenant", "seq", "wall_ms", "buckets", "tiers",
                 "stages")

    def __init__(self, rid: int, tenant: str, seq: int, wall_ms: float,
                 buckets: dict[str, float], tiers: dict[str, str],
                 stages: list[int]):
        self.rid = rid
        self.tenant = tenant
        self.seq = seq
        self.wall_ms = wall_ms
        #: ordered bucket name -> milliseconds
        self.buckets = buckets
        #: transport bucket -> negotiated tier label (when known)
        self.tiers = tiers
        self.stages = stages

    @property
    def sum_ms(self) -> float:
        return sum(self.buckets.values())

    @property
    def residual_ms(self) -> float:
        """Measured wall minus the bucket sum (clock skew + untracked
        gaps); the tolerance check is against its magnitude."""
        return self.wall_ms - self.sum_ms

    def ok(self, tol: float = 0.10) -> bool:
        """True when the buckets sum to within ``tol`` (fractional) of
        the measured end-to-end latency — the acceptance bar."""
        if self.wall_ms <= 0:
            return False
        return abs(self.residual_ms) <= tol * self.wall_ms

    def to_json(self) -> dict:
        return {"rid": self.rid, "tenant": self.tenant, "seq": self.seq,
                "wall_ms": round(self.wall_ms, 4),
                "sum_ms": round(self.sum_ms, 4),
                "residual_ms": round(self.residual_ms, 4),
                "buckets_ms": {k: round(v, 4)
                               for k, v in self.buckets.items()},
                "tiers": dict(self.tiers)}


def _index_request_spans(spans):
    """(by_rid, by_seq) lookup tables for the serve/stage span names
    attribution reads."""
    door: dict[int, dict[str, dict]] = {}
    gather: dict[int, dict] = {}
    stage: dict[int, dict[int, dict[str, dict]]] = {}
    for s in spans:
        name = s.get("name", "")
        args = s.get("args") or {}
        if name in ("serve.request", "serve.admission_wait",
                    "serve.deliver"):
            rid = args.get("rid")
            if rid is not None:
                door.setdefault(int(rid), {})[name] = s
            continue
        if name == "serve.gather":
            seq = args.get("seq")
            if seq is not None:
                gather[int(seq)] = s
            continue
        m = _STAGE_RE.match(name)
        if m is not None:
            seq = args.get("seq")
            if seq is not None:
                stage.setdefault(int(seq), {}) \
                    .setdefault(int(m.group(1)), {})[m.group(2)] = s
    return door, gather, stage


def attribute_request(spans, rid: int, *,
                      hop_tiers=None) -> RequestAttribution | None:
    """Fold one request's spans into budget buckets (None when the
    request was not sampled or its root span is missing).

    ``spans`` is any merged span list on one timeline — the process
    tracer after ``collect_trace``, or ``ClusterView.spans()``.
    ``hop_tiers`` (optional, one entry per chain hop starting at the
    dispatcher->stage0 edge) labels the transport buckets with their
    negotiated tier."""
    return _attribute_indexed(_index_request_spans(spans), rid,
                              hop_tiers=hop_tiers)


def _attribute_indexed(index, rid: int, *,
                       hop_tiers=None) -> RequestAttribution | None:
    door, gather, stage = index
    mine = door.get(int(rid))
    if not mine or "serve.request" not in mine:
        return None
    root = mine["serve.request"]
    args = root.get("args") or {}
    seq = args.get("seq")
    if seq is None:
        return None
    seq = int(seq)
    t0 = root["ts_us"]
    end = t0 + root["dur_us"]
    buckets: dict[str, float] = {}
    tiers: dict[str, str] = {}

    def put(name: str, us: float) -> None:
        # clock skew can push a cross-process boundary slightly
        # negative; clamp — the residual check still sees the error
        buckets[name] = max(0.0, us) / 1e3

    adm = mine.get("serve.admission_wait")
    adm_end = adm["ts_us"] + adm["dur_us"] if adm is not None else t0
    put("admission", adm_end - t0)
    g = gather.get(seq)
    g_end = g["ts_us"] + g["dur_us"] if g is not None else adm_end
    put("gather", g_end - adm_end)
    prev_end = g_end
    stages = sorted(stage.get(seq, ()))
    host_sync_us = 0.0
    for hop, k in enumerate(stages):
        infer = stage[seq][k].get("infer")
        if infer is None:
            continue
        tier = None
        if hop_tiers is not None and hop < len(hop_tiers):
            tier = hop_tiers[hop]
        put(f"transport.hop{hop}", infer["ts_us"] - prev_end)
        if tier:
            tiers[f"transport.hop{hop}"] = str(tier)
        hs = stage[seq][k].get("host_sync")
        hs_us = hs["dur_us"] if hs is not None else 0
        host_sync_us += hs_us
        put(f"stage{k}", infer["dur_us"] - hs_us)
        prev_end = infer["ts_us"] + infer["dur_us"]
    put("host_sync", host_sync_us)
    dl = mine.get("serve.deliver")
    if dl is not None:
        put("transport.result", dl["ts_us"] - prev_end)
        if hop_tiers is not None and len(hop_tiers) > len(stages):
            tiers["transport.result"] = str(hop_tiers[len(stages)])
        put("result_edge", (dl["ts_us"] + dl["dur_us"]) - dl["ts_us"])
    else:
        put("transport.result", end - prev_end)
        put("result_edge", 0.0)
    return RequestAttribution(
        rid=int(rid), tenant=str(args.get("tenant", "?")), seq=seq,
        wall_ms=root["dur_us"] / 1e3, buckets=buckets, tiers=tiers,
        stages=stages)


def attribute_sampled(spans, *, hop_tiers=None) -> list[RequestAttribution]:
    """Attribution for EVERY sampled request found in ``spans``
    (one per ``serve.request`` root span), wall-latency ascending —
    index into it for the p50/p99 requests.  The span list is indexed
    ONCE, shared by every request's fold."""
    index = _index_request_spans(spans)
    out = []
    for rid in index[0]:
        rep = _attribute_indexed(index, rid, hop_tiers=hop_tiers)
        if rep is not None:
            out.append(rep)
    out.sort(key=lambda r: r.wall_ms)
    return out


#: the door-side (trace-free) bucket names, in timeline order
DOOR_BUCKETS = ("admission", "gather", "chain", "result_edge")


class DoorAttribution:
    """Always-on per-tenant bucket histograms at the front door.

    Four timestamps per delivered unit tile its timeline exactly:
    admitted -> popped (``admission``), popped -> submitted
    (``gather``), submitted -> demux receipt (``chain`` — everything
    inside the deployed chain), demux -> client bytes written
    (``result_edge``).  No tracing required; this is what
    ``monitor --serve`` renders and the stats reply carries."""

    def __init__(self):
        self._lock = threading.Lock()
        self._tenants: dict[str, dict[str, LatencyHistogram]] = {}

    def _hists(self, tenant: str) -> dict[str, LatencyHistogram]:
        with self._lock:
            h = self._tenants.get(tenant)
            if h is None:
                h = self._tenants[tenant] = {
                    k: LatencyHistogram()
                    for k in DOOR_BUCKETS + ("e2e",)}
            return h

    def record(self, tenant: str, *, queued: float, popped: float,
               submitted: float, demuxed: float, delivered: float
               ) -> None:
        """Fold one unit's timestamps (``perf_counter`` seconds) in.
        Out-of-order stamps clamp to zero-width buckets."""
        h = self._hists(tenant)
        popped = max(queued, popped)
        submitted = max(popped, submitted)
        demuxed = max(submitted, demuxed)
        delivered = max(demuxed, delivered)
        h["admission"].record(popped - queued)
        h["gather"].record(submitted - popped)
        h["chain"].record(demuxed - submitted)
        h["result_edge"].record(delivered - demuxed)
        h["e2e"].record(delivered - queued)

    def summary(self) -> dict:
        """Per-tenant bucket summaries in milliseconds (JSON-ready):
        ``{tenant: {bucket: {count, p50, p99, ...}}}``."""
        with self._lock:
            tenants = {t: dict(h) for t, h in self._tenants.items()}
        return {t: {k: hist.summary(scale=1e3)
                    for k, hist in h.items()}
                for t, h in sorted(tenants.items())}
