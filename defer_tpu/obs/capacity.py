"""Capacity accounting: live MFU / roofline utilization and the
prediction-drift auditor.

The analytic halves already exist — ``graph/analysis.py`` counts FLOPs,
``utils/hw.py`` publishes per-generation peaks, ``plan/cost.py`` prices
the roofline — and the runtime measures per-stage infer histograms on
every frame.  This module joins them:

* :func:`stage_flops_bytes` / :class:`CapacityModel` — per-stage
  analytic FLOPs and HBM bytes for a deployed partition, and the
  derived live metrics: **MFU** (achieved FLOP/s over the chip peak)
  and **roofline utilization** (the model's best-case stage seconds
  over the measured seconds).  The ``hw.peak_flops`` contract carries
  through: an unknown chip generation has NO peak, so MFU is ``None``
  (rendered ``-``), never a number fabricated against a guessed peak.
* :class:`DriftAuditor` — scores the deployed plan's per-stage service
  predictions (:func:`~defer_tpu.plan.calibrate.predict_stage_service_s`)
  against the live window-bounded measurements every monitor interval;
  sustained relative error past the threshold emits ONE ``model_drift``
  flight-recorder event per episode (the same sustain/re-arm discipline
  as ``StragglerDetector``), so a cost model going stale is a recorded
  fact with numbers attached, not a vibe.

Node-side MFU (the ``stats`` / ``obs_push`` fields) uses
:func:`achieved_mfu` with the per-stage FLOPs the dispatcher ships in
the deploy message — the node knows its own chip generation; the
monitor-side :class:`CapacityModel` recomputes the same figure for
views that only have plan JSON.
"""

from __future__ import annotations

import dataclasses

from ..utils import hw
from .cluster import SERVICE_WINDOW
from .events import emit as emit_event


def stage_flops_bytes(graph, node_names, *, batch: int = 1
                      ) -> tuple[float, float]:
    """(flops, hbm bytes moved) of one stage's nodes at ``batch`` — the
    same per-node accounting as the cost model's roofline
    (``StageCostModel.node_seconds``): every node reads its inputs and
    writes its output through HBM."""
    from ..graph.analysis import node_flops
    batch = max(1, int(batch))
    flops = moved = 0.0
    for name in node_names:
        node = graph.nodes[name]
        flops += node_flops(graph, name)
        moved += sum(graph.out_spec(i).size * graph.out_spec(i).dtype.itemsize
                     for i in node.inputs)
        moved += node.out_spec.size * node.out_spec.dtype.itemsize
    return flops * batch, moved * batch


def achieved_mfu(flops: float, seconds: float,
                 peak_flops_s: float) -> float | None:
    """MFU of one stage interval: achieved FLOP/s over the chip peak.
    ``None`` when there is no honest denominator (unknown peak) or no
    measurement — callers render it as ``-``, never as 0.0 (a real 0%
    and "we cannot know" must stay distinguishable)."""
    if peak_flops_s <= 0 or seconds <= 0 or flops <= 0:
        return None
    return flops / (seconds * peak_flops_s)


def stages_from_cuts(graph, cuts) -> list[list[str]]:
    """Topo-order node names per stage for a ``cuts`` partition."""
    order = graph.topo_order
    pos = {n: i for i, n in enumerate(order)}
    bounds = [0] + [pos[c] + 1 for c in cuts] + [len(order)]
    return [order[bounds[k]:bounds[k + 1]]
            for k in range(len(bounds) - 1)]


class CapacityModel:
    """Analytic per-stage capacity of a deployed partition, joined with
    measurements on demand.

    ``gen`` anchors the peaks; ``peak_flops_s`` / ``hbm_bw_s`` override
    them explicitly (e.g. from a plan's ``cost_model`` dict).  Unknown
    generation and no override = no peak = MFU/roofline ``None``.
    """

    def __init__(self, graph, cuts, *, batch: int = 1,
                 gen: str | None = None,
                 peak_flops_s: float | None = None,
                 hbm_bw_s: float | None = None):
        self.graph = graph
        self.cuts = list(cuts)
        self.batch = max(1, int(batch))
        self.gen = gen or "unknown"
        # NO v5e fallback here, unlike the cost model: the cost model
        # needs relative weights on any host, but MFU against a
        # borrowed peak is a fabricated percentage (utils/hw.py policy)
        self.peak_flops_s = float(peak_flops_s) if peak_flops_s \
            else hw.peak_flops(self.gen)
        self.hbm_bw_s = float(hbm_bw_s) if hbm_bw_s \
            else hw.hbm_bandwidth(self.gen)
        self.stages = stages_from_cuts(graph, self.cuts)
        self.stage_flops: list[float] = []
        self.stage_bytes: list[float] = []
        for names in self.stages:
            f, b = stage_flops_bytes(graph, names, batch=self.batch)
            self.stage_flops.append(f)
            self.stage_bytes.append(b)

    @property
    def num_stages(self) -> int:
        return len(self.stages)

    def roofline_s(self, stage: int) -> float | None:
        """Best-case stage seconds under the roofline: compute-bound at
        the peak or bandwidth-bound at HBM rate, whichever dominates.
        ``None`` without honest peaks."""
        if self.peak_flops_s <= 0 or self.hbm_bw_s <= 0:
            return None
        return max(self.stage_flops[stage] / self.peak_flops_s,
                   self.stage_bytes[stage] / self.hbm_bw_s)

    def mfu(self, stage: int, measured_s: float) -> float | None:
        return achieved_mfu(self.stage_flops[stage], measured_s,
                            self.peak_flops_s)

    def roofline_util(self, stage: int, measured_s: float
                      ) -> float | None:
        """Fraction of the roofline bound achieved: 1.0 = running at
        the model's best case (compute- or bandwidth-limited)."""
        best = self.roofline_s(stage)
        if best is None or measured_s <= 0:
            return None
        return best / measured_s

    def chain_mfu(self, bottleneck_s: float) -> float | None:
        """Pipeline-level MFU: total graph FLOPs over what the chain's
        aggregate silicon could do in one pipeline interval — the same
        figure ``benchmarks/run.py`` publishes (``num_stages`` chips
        each spend ``bottleneck_s`` per frame at steady state)."""
        if self.peak_flops_s <= 0 or bottleneck_s <= 0:
            return None
        total = sum(self.stage_flops)
        return total / (bottleneck_s * self.peak_flops_s
                        * max(1, self.num_stages))

    def to_json(self) -> dict:
        return {
            "gen": self.gen, "batch": self.batch,
            "peak_flops_s": self.peak_flops_s, "hbm_bw_s": self.hbm_bw_s,
            "stage_flops": [float(f) for f in self.stage_flops],
            "stage_bytes": [float(b) for b in self.stage_bytes],
            "roofline_ms": [
                None if (r := self.roofline_s(k)) is None
                else round(r * 1e3, 6) for k in range(self.num_stages)],
        }


# ---------------------------------------------------------------------------
# prediction-drift auditing
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class DriftFlag:
    stage: int
    predicted_ms: float
    measured_ms: float
    rel_err: float         #: (measured - predicted) / predicted, signed
    intervals: int         #: consecutive observe() calls sustained

    def to_json(self) -> dict:
        return {"stage": self.stage,
                "predicted_ms": round(self.predicted_ms, 4),
                "measured_ms": round(self.measured_ms, 4),
                "rel_err": round(self.rel_err, 4),
                "intervals": self.intervals}


class DriftAuditor:
    """Scores per-stage service predictions against live measurement.

    ``predicted_ms`` is the measurement-aligned prediction
    (:func:`~defer_tpu.plan.calibrate.predict_stage_service_s`, in ms).
    Call :meth:`observe` once per monitor interval: a stage whose
    |relative error| exceeded ``threshold`` for ``sustain`` consecutive
    calls is flagged and emits ONE ``model_drift`` event; the event
    re-arms when the stage drops back under the threshold (same
    discipline as ``StragglerDetector``).  Measurements are
    window-bounded (``ClusterView.stage_service_ms(window=...)``) so a
    regime shift shows up within a few pushes instead of being averaged
    into the lifetime fold.

    :attr:`last` keeps the most recent per-stage audit rows
    (``{stage: {"pred_ms", "meas_ms", "err"}}``) for the monitor's
    PRED/MEAS/ERR% columns.
    """

    def __init__(self, predicted_ms, *, threshold: float = 0.25,
                 sustain: int = 2, window: int = SERVICE_WINDOW):
        self.predicted_ms = [float(v) for v in predicted_ms]
        self.threshold = float(threshold)
        self.sustain = max(1, int(sustain))
        self.window = max(2, int(window))
        self._over: dict[int, int] = {}
        self._emitted: set[int] = set()
        self.last: dict[int, dict] = {}

    def audit(self, view) -> dict[int, dict]:
        """One pass of predicted-vs-measured, no flagging: per-stage
        ``{"pred_ms", "meas_ms", "err"}`` (err ``None`` until a stage
        has both numbers)."""
        measured = view.stage_service_ms(window=self.window)
        rows: dict[int, dict] = {}
        for k, pred in enumerate(self.predicted_ms):
            meas = float(measured.get(k, 0.0))
            err = (meas - pred) / pred if pred > 0 and meas > 0 else None
            rows[k] = {"pred_ms": round(pred, 4),
                       "meas_ms": round(meas, 4),
                       "err": None if err is None else round(err, 4)}
        self.last = rows
        return rows

    def observe(self, view) -> list[DriftFlag]:
        rows = self.audit(view)
        flags = []
        for k, row in rows.items():
            err = row["err"]
            if err is not None and abs(err) > self.threshold:
                self._over[k] = self._over.get(k, 0) + 1
            else:
                self._over[k] = 0
                self._emitted.discard(k)
            if self._over[k] >= self.sustain:
                flag = DriftFlag(stage=k, predicted_ms=row["pred_ms"],
                                 measured_ms=row["meas_ms"],
                                 rel_err=err, intervals=self._over[k])
                flags.append(flag)
                if k not in self._emitted:
                    self._emitted.add(k)
                    emit_event("model_drift", **flag.to_json())
        return flags
