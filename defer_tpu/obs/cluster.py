"""Dispatcher-side live observability: cluster aggregation, clock
alignment, and straggler detection over the chain's push telemetry.

The chain's nodes push ``{"cmd": "obs_push"}`` control frames (a
subscription started by ``{"cmd": "obs_subscribe"}`` on any control
connection — ``runtime/node.py``, ``obs/report.py``); this module is the
receiving half:

* :func:`estimate_clock_offset` — NTP's simplest form over a ctrl
  socket: N ping-pong rounds, keep the offset from the minimum-RTT
  sample.  The dispatcher then ships a ``clock_adjust`` back so the
  node's :attr:`Tracer._wall0_us` anchor lands on the dispatcher's
  timeline and every process's spans share one coherent Perfetto axis.
* :class:`ClusterView` — merges pushes into a rolling per-stage /
  per-replica model (throughput, latency percentiles, queue depths and
  watermarks, bytes/s) with a bounded per-node history; identifies the
  live bottleneck stage by the BACKPRESSURE EDGE (queue-watermark
  saturation stops at the bottleneck: every stage upstream of it has a
  saturated tx queue, the bottleneck's own tx is drained) falling back
  to per-stage service-time estimates.
* :class:`StragglerDetector` — compares the live model against the
  active plan's per-stage expectations (``stage_effective_ms``) and
  flags sustained deviation, sustained backpressure, or a stalled
  stage; :meth:`StragglerDetector.suggest` feeds the view's rows into
  the existing :func:`defer_tpu.plan.replan.replan` machinery to emit a
  :class:`~defer_tpu.plan.replan.ReplanResult` while the stream is
  still in flight.

Transport imports are deferred inside functions: ``transport.framed``
itself imports ``defer_tpu.obs``, and this module must stay importable
from ``obs/__init__``.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time

from .events import emit as emit_event
from .events import merge_events, recorder
from .trace import Tracer, tracer

#: a queue watermark at >= this fraction of its depth counts as saturated
SATURATION_FRAC = 0.9

#: default window (in pushes) for the rolling service estimates — ~2 s
#: at the default 250 ms report interval.  Shared with the capacity
#: plane's drift auditor (obs/capacity.py imports it from here).
SERVICE_WINDOW = 8


# ---------------------------------------------------------------------------
# clock alignment
# ---------------------------------------------------------------------------

def estimate_clock_offset(sock, *, rounds: int = 8,
                          local: Tracer | None = None) -> dict:
    """Estimate the peer tracer's timeline offset over a ctrl socket.

    N ``clock_probe`` ping-pong rounds; per round the peer's reported
    ``now_us`` is compared against the local midpoint estimate
    ``t0 + rtt/2``.  The round with the minimum RTT bounds the error
    tightest (the probe least delayed by queueing), so its offset is the
    estimate — NTP's simplest form.  Returns ``{"offset_us", "rtt_us",
    "rounds"}`` where ``offset_us`` is (peer timeline − local timeline):
    ship ``-offset_us`` back in a ``clock_adjust`` to align the peer.
    """
    from ..transport.framed import K_CTRL, recv_frame, send_ctrl

    tr = local or tracer()
    best_rtt = None
    best_off = 0.0
    for i in range(max(1, rounds)):
        t0 = tr.now_us()
        send_ctrl(sock, {"cmd": "clock_probe", "echo": i})
        while True:
            kind, msg = recv_frame(sock)
            if kind == K_CTRL and isinstance(msg, dict) \
                    and msg.get("cmd") == "clock_probe_reply" \
                    and msg.get("echo") == i:
                break
        t1 = tr.now_us()
        rtt = t1 - t0
        off = float(msg["t_us"]) - (t0 + rtt / 2.0)
        if best_rtt is None or rtt < best_rtt:
            best_rtt, best_off = rtt, off
    return {"offset_us": best_off, "rtt_us": best_rtt,
            "rounds": max(1, rounds)}


def align_clock(sock, *, rounds: int = 8,
                local: Tracer | None = None) -> dict:
    """Estimate the peer's offset and ship the correcting
    ``clock_adjust`` (ACKed), so the peer's future AND buffered spans
    land on the local timeline.  Returns the estimate dict."""
    from ..transport.framed import K_ACK, recv_expect, send_ctrl

    est = estimate_clock_offset(sock, rounds=rounds, local=local)
    send_ctrl(sock, {"cmd": "clock_adjust",
                     "offset_us": -int(round(est["offset_us"]))})
    recv_expect(sock, K_ACK)
    return est


def expected_stage_ms(plan) -> list[float]:
    """Per-stage expected service milliseconds from a solved plan: the
    replica-divided ``stage_effective_ms`` when the plan is replicated,
    else the plain ``stage_cost_ms`` (max of compute and hop comm)."""
    doc = plan.to_json() if hasattr(plan, "to_json") else dict(plan)
    return list(doc.get("stage_effective_ms") or doc["stage_cost_ms"])


# ---------------------------------------------------------------------------
# cluster view
# ---------------------------------------------------------------------------

def _p50_ms(summ) -> float:
    if not isinstance(summ, dict) or not summ.get("count"):
        return 0.0
    return float(summ.get("p50", summ.get("mean", 0.0))) * 1e3


def _service_ms(push: dict) -> float:
    """One push's per-replica service-time estimate: the slowest of the
    three phases that each own a thread in the overlapped node loop
    (decode on rx, stage infer, encode on tx) — whichever is largest
    bounds that replica's steady-state rate."""
    lat = push.get("latency") or {}
    return max(_p50_ms(lat.get("infer_s")),
               _p50_ms(lat.get("decode_s")),
               _p50_ms(lat.get("encode_s")))


def _win_mean_ms(history, phase: str) -> float | None:
    """Delta-mean (ms) of one latency phase over a push window: the
    exact ``sum``/``count`` fields of the first and last push in the
    window subtract cleanly (percentiles do not), so the estimate
    reflects ONLY the frames of the current window — a regime shift
    shows up within a few pushes instead of being averaged into the
    lifetime fold.  ``None`` when the phase gained no samples."""
    first = (history[0][1].get("latency") or {}).get(phase) or {}
    last = (history[-1][1].get("latency") or {}).get(phase) or {}
    n = int(last.get("count", 0)) - int(first.get("count", 0))
    if n <= 0:
        return None
    return (float(last.get("sum", 0.0))
            - float(first.get("sum", 0.0))) / n * 1e3


class _Node:
    """Rolling per-node state: identity + a bounded push history."""

    __slots__ = ("ident", "addr", "history", "err", "events_dropped")

    def __init__(self, ident: dict, addr: str | None, history: int):
        self.ident = ident
        self.addr = addr
        self.history: collections.deque = collections.deque(maxlen=history)
        self.err: BaseException | None = None
        self.events_dropped = 0


class ClusterView:
    """Rolling per-stage / per-replica model of a live chain.

    Feed it either by :meth:`connect` (dial each node, clock-align,
    subscribe, one reader thread per node) or by calling :meth:`ingest`
    with ``obs_push`` payloads directly (tests, embedded dispatchers).
    """

    def __init__(self, *, history: int = 240, span_buffer: int = 4096,
                 event_buffer: int = 4096):
        self._lock = threading.Lock()
        self._nodes: dict = {}
        self._history = history
        self._spans: collections.deque = collections.deque(
            maxlen=span_buffer)
        #: cluster-merged flight-recorder events, arrival order
        #: (obs/events.py rides the obs_push frames here)
        self._events: collections.deque = collections.deque(
            maxlen=event_buffer)
        #: sum of every node's reported ring evictions (a nonzero total
        #: means the merged log has gaps — surfaced by monitor --events)
        self.events_dropped = 0
        self._socks: list = []
        self._threads: list[threading.Thread] = []
        self._closed = threading.Event()
        #: per-addr clock-offset estimates from :meth:`connect`
        self.clock_offsets: dict[str, dict] = {}

    # -- feeding -----------------------------------------------------------

    @staticmethod
    def _key(ident: dict, addr: str | None):
        stage = ident.get("stage")
        if stage is None:
            return ("addr", addr or ident.get("port"))
        return (int(stage), ident.get("replica"))

    def ingest(self, push: dict, addr: str | None = None) -> None:
        """Merge one ``obs_push`` payload into the rolling model."""
        ident = push.get("node") or {}
        key = self._key(ident, addr)
        with self._lock:
            node = self._nodes.get(key)
            if node is None:
                node = self._nodes[key] = _Node(ident, addr, self._history)
            node.ident = ident
            node.history.append((time.monotonic(), push))
            spans = (push.get("trace") or {}).get("spans") or ()
            self._spans.extend(spans)
            ev_doc = push.get("events") or {}
            self._events.extend(ev_doc.get("events") or ())
            dropped = ev_doc.get("dropped")
            if dropped is not None:
                # per-node lifetime counts: keep the max seen per node
                node.events_dropped = int(dropped)
                self.events_dropped = sum(
                    getattr(nd, "events_dropped", 0)
                    for nd in self._nodes.values())

    def connect(self, addrs, *, interval_ms: float = 250.0,
                spans: bool = False, span_limit: int = 256,
                align_clocks: bool = False, probe_clocks: bool = True,
                timeout_s: float = 30.0,
                clock_rounds: int = 8,
                reconnect: bool = False) -> "ClusterView":
        """Dial every node address, subscribe to its push stream, and
        consume pushes on one daemon reader thread per node until
        :meth:`close`.  A node that dies mid-watch marks its rows dead
        instead of killing the view.

        ``reconnect=True`` makes each reader SURVIVE node restarts: the
        failover supervisor respawns a killed replica on its old port,
        so the reader redials that address with the transport's jittered
        ``connect_retry`` backoff, re-subscribes, and resumes — the
        follow-mode monitor keeps tailing across the kill instead of
        going silent.  Resumed streams dedup naturally: a respawned
        process's events carry a fresh ``proc`` identity and a fresh
        subscription's cursor starts at its current ring position, and
        the consumer-side ``merge_events`` collapses any overlap on the
        ``(proc, seq)`` key.

        Clocks: ``probe_clocks`` (default) ESTIMATES each node's offset
        (filling :attr:`clock_offsets`) without touching its tracer —
        watching must be passive, and a monitor that re-anchored nodes
        to ITS OWN timeline would undo the dispatcher's earlier
        alignment and re-skew the final trace export.  Pass
        ``align_clocks=True`` only when this process IS the trace
        collector (e.g. ``ChainDispatcher.watch`` from the dispatcher,
        or ``monitor --align``)."""
        from ..transport.framed import send_ctrl

        self._sub = {"interval_ms": interval_ms, "spans": bool(spans),
                     "span_limit": int(span_limit)}
        self._reconnect = bool(reconnect)
        self._redial_timeout_s = float(timeout_s)
        for addr in addrs:
            host, _, port = str(addr).rpartition(":")
            sock = self._dial(host or "127.0.0.1", int(port), timeout_s)
            if align_clocks:
                self.clock_offsets[str(addr)] = align_clock(
                    sock, rounds=clock_rounds)
            elif probe_clocks:
                self.clock_offsets[str(addr)] = estimate_clock_offset(
                    sock, rounds=clock_rounds)
            send_ctrl(sock, {"cmd": "obs_subscribe",
                             "interval_ms": interval_ms,
                             "spans": bool(spans),
                             "span_limit": int(span_limit)})
            self._socks.append(sock)
            t = threading.Thread(target=self._reader,
                                 args=(sock, str(addr)),
                                 daemon=True, name="cluster-view-rx")
            t.start()
            self._threads.append(t)
        return self

    @staticmethod
    def _dial(host: str, port: int, timeout_s: float):
        from ..transport.framed import connect_retry
        return connect_retry(host, port, timeout_s)

    def _reader(self, sock, addr: str) -> None:
        from ..transport.framed import K_CTRL, K_END, recv_frame, send_ctrl
        while True:
            try:
                while not self._closed.is_set():
                    kind, msg = recv_frame(sock)
                    if kind == K_END:
                        return
                    if kind == K_CTRL and isinstance(msg, dict) \
                            and msg.get("cmd") == "obs_push":
                        self.ingest(msg, addr)
                return
            except (OSError, ConnectionError, ValueError) as e:
                with self._lock:
                    for node in self._nodes.values():
                        if node.addr == addr:
                            node.err = e
                if self._closed.is_set():
                    return
                # a node dying mid-watch is itself a flight-recorder
                # fact: it lands in THIS process's ring and therefore in
                # the merged log (the dead node can no longer push)
                self._events.append(emit_event(
                    "node_dead", addr=addr, error=repr(e)))
                if not getattr(self, "_reconnect", False):
                    return
                # survive the restart: the failover supervisor respawns
                # a killed replica on its OLD port, so redial the same
                # address with the transport's jittered backoff and
                # re-subscribe (a fresh subscription's event cursor
                # starts at the new ring's position; merge_events dedups
                # any overlap on (proc, seq))
                host, _, port = addr.rpartition(":")
                try:
                    sock = self._dial(host or "127.0.0.1", int(port),
                                      getattr(self, "_redial_timeout_s",
                                              30.0))
                    send_ctrl(sock, {"cmd": "obs_subscribe",
                                     **self._sub})
                except (OSError, ConnectionError):
                    return   # node stayed dead past the dial deadline
                if self._closed.is_set():
                    try:
                        sock.close()
                    except OSError:
                        pass
                    return
                self._socks.append(sock)
                with self._lock:
                    for node in self._nodes.values():
                        if node.addr == addr:
                            node.err = None

    def close(self) -> None:
        """Unsubscribe (best-effort END) and drop every connection."""
        from ..transport.framed import send_end
        self._closed.set()
        for s in self._socks:
            try:
                send_end(s)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=5.0)

    # -- the rolling model -------------------------------------------------

    def _rate(self, node: _Node, field, window: int = 5) -> float:
        """Delta-rate of a cumulative counter over the last few pushes."""
        h = list(node.history)[-window:]
        if len(h) < 2:
            return 0.0
        (t0, p0), (t1, p1) = h[0], h[-1]
        dt = t1 - t0
        if dt <= 0:
            return 0.0
        return (field(p1) - field(p0)) / dt

    def rows(self) -> list[dict]:
        """Per-replica live rows, stage order (dispatcher-addressed rows
        last).  Rates are deltas over the last few pushes; percentiles
        come from the node's cumulative histograms."""
        out = []
        with self._lock:
            nodes = list(self._nodes.items())
        now = time.monotonic()
        for key, node in nodes:
            if not node.history:
                continue
            t_last, last = node.history[-1]
            q = last.get("queues") or {}
            lat = last.get("latency") or {}
            cnt = last.get("counters") or {}
            # watermarks are per-interval peaks: report the max over the
            # last few pushes so a burst is visible past one interval
            recent = [p for _, p in list(node.history)[-5:]]

            def peak(field: str) -> float:
                return max(((p.get("queues") or {}).get(field, 0)
                            for p in recent), default=0)
            row = {
                "stage": node.ident.get("stage"),
                "replica": node.ident.get("replica"),
                # branched stage graphs (docs/TRANSPORT.md): the branch
                # path this vertex rides, and the join width when this
                # vertex merges P paths — what the monitor's BR column
                # renders so a bottleneck highlight names the branch
                "branch": node.ident.get("branch"),
                "join": node.ident.get("join"),
                "name": node.ident.get("name"),
                # negotiated OUTBOUND transport tier of the node's hop
                # (tcp / local / shm / auto-until-negotiated) —
                # distinguishes wire-bound rows from colocated
                # fast-path ones — plus the hop's degraded-offer count
                # (a tcp row with fallbacks is a hop that WANTED a
                # colocated tier; the monitor marks it "tcp!")
                "tier": node.ident.get("tier"),
                "tier_fallbacks": node.ident.get("tier_fallbacks", 0),
                "addr": node.addr,
                "pushes": len(node.history),
                "age_s": round(now - t_last, 3),
                "alive": node.err is None,
                "processed": last.get("processed", 0),
                "throughput_per_s": round(self._rate(
                    node, lambda p: p.get("processed", 0)), 3),
                "rx_bytes_per_s": round(self._rate(
                    node, lambda p: (p.get("counters") or {})
                    .get("rx_bytes", 0)), 1),
                "tx_bytes_per_s": round(self._rate(
                    node, lambda p: (p.get("counters") or {})
                    .get("tx_bytes", 0)), 1),
                "infer_ms": {k: round(float(
                    (lat.get("infer_s") or {}).get(k, 0.0)) * 1e3, 4)
                    for k in ("p50", "p95", "p99")},
                # host-sync distribution (np.asarray materialization
                # around the compute loop): an ici hop's row shows
                # count == 0 — the observable proof the device-resident
                # path skipped the host round-trip entirely
                "host_sync_ms": {
                    "p50": round(float((lat.get("host_sync_s") or {})
                                       .get("p50", 0.0)) * 1e3, 4),
                    "count": int((lat.get("host_sync_s") or {})
                                 .get("count", 0))},
                # the infer X-ray (obs/profile.py): dispatch = the jit
                # call returning (host-side cost), device =
                # block_until_ready — the monitor's DISP/DEV columns;
                # count 0 (rendered "-") from a pre-profiling node
                "dispatch_ms": {
                    "p50": round(float((lat.get("dispatch_s") or {})
                                       .get("p50", 0.0)) * 1e3, 4),
                    "count": int((lat.get("dispatch_s") or {})
                                 .get("count", 0))},
                "device_ms": {
                    "p50": round(float((lat.get("device_s") or {})
                                       .get("p50", 0.0)) * 1e3, 4),
                    "count": int((lat.get("device_s") or {})
                                 .get("count", 0))},
                "queue_ms": {
                    "p50": round(float((lat.get("queue_s") or {})
                                       .get("p50", 0.0)) * 1e3, 4),
                    "count": int((lat.get("queue_s") or {})
                                 .get("count", 0))},
                # compile/memory telemetry: None from old-vintage or
                # jax-less processes (rendered "-", never a fake 0)
                "mem_bytes": last.get("mem_bytes"),
                "recompiles": last.get("recompiles"),
                "service_ms": round(_service_ms(last), 4),
                # window-bounded rolling service (delta-means over the
                # last few pushes) — the current-regime estimate the
                # drift auditor and suggest() score against
                "service_win_ms": round(
                    self._windowed_service_ms(node, SERVICE_WINDOW), 4),
                # capacity accounting shipped by the node itself
                # (deploy message carries the stage's analytic FLOPs;
                # the node owns its chip generation).  mfu is None —
                # rendered "-" — when the peak is unknown.
                "flops": (last.get("capacity") or {}).get("flops"),
                "mfu": (last.get("capacity") or {}).get("mfu"),
                "achieved_flops_s": (last.get("capacity") or {})
                .get("achieved_flops_s"),
                "rx_q": q.get("rx", 0), "tx_q": q.get("tx", 0),
                "rx_hi": peak("rx_hi"), "tx_hi": peak("tx_hi"),
                "rx_depth": q.get("rx_depth", 0),
                "tx_depth": q.get("tx_depth", 0),
                "inflight": q.get("inflight", 0),
                "tx_frames": cnt.get("tx_frames", 0),
                "rx_frames": cnt.get("rx_frames", 0),
                "spans_dropped": (last.get("trace") or {})
                .get("dropped", 0),
            }
            out.append(row)
        out.sort(key=lambda r: ((0, r["stage"], r["replica"] or 0)
                                if r["stage"] is not None
                                else (1, 0, 0)))
        return out

    def stats_rows(self) -> list[dict]:
        """The latest push per node reshaped like a
        ``ChainDispatcher.stats`` reply row — directly consumable by
        :func:`defer_tpu.plan.replan.measured_stage_seconds` / replan."""
        out = []
        with self._lock:
            nodes = list(self._nodes.values())
        for node in nodes:
            if not node.history:
                continue
            _, last = node.history[-1]
            lat = last.get("latency") or {}
            out.append({
                "stage": node.ident.get("stage"),
                "name": node.ident.get("name"),
                "replica": node.ident.get("replica"),
                "fan_in": node.ident.get("fan_in", 1),
                "processed": last.get("processed", 0),
                "infer_latency_s": lat.get("infer_s") or {"count": 0},
            })
        return out

    def spans(self) -> list[dict]:
        """Recent pushed span samples (bounded buffer)."""
        with self._lock:
            return list(self._spans)

    def events(self, *, include_local: bool = True) -> list[dict]:
        """The cluster-merged flight-recorder log: every watched node's
        pushed events (plus, by default, this process's own ring — a
        dispatcher/front door colocated with the monitor) ordered by
        the clock-aligned timestamp with per-process seq as the tie
        break (:func:`~defer_tpu.obs.events.merge_events`)."""
        with self._lock:
            batch = list(self._events)
        if include_local:
            # the view's node_dead markers are already copies of local
            # ring entries — dedup on (proc, seq)
            seen = {(e.get("proc"), e.get("seq")) for e in batch}
            batch += [e for e in recorder().snapshot()
                      if (e.get("proc"), e.get("seq")) not in seen]
        return merge_events(batch)

    def take_events(self) -> list[dict]:
        """Drain the NODE-pushed events accumulated since the last call
        (arrival order) — the monitor's incremental read; merge with
        :func:`merge_events` per batch when rendering."""
        out = []
        with self._lock:
            while self._events:
                out.append(self._events.popleft())
        return out

    # -- bottleneck identification ----------------------------------------

    def _stage_map(self) -> dict[int, list[dict]]:
        stages: dict[int, list[dict]] = {}
        for r in self.rows():
            if r["stage"] is not None:
                stages.setdefault(int(r["stage"]), []).append(r)
        return stages

    @staticmethod
    def _saturated(row: dict, side: str) -> bool:
        depth = row.get(f"{side}_depth") or 0
        return depth > 0 and row.get(f"{side}_hi", 0) \
            >= SATURATION_FRAC * depth

    @staticmethod
    def _eff_ms(reps: list[dict]) -> float:
        """Replica-divided effective service of one stage's rows: the
        mean replica service time over the replica count — THE formula
        shared by bottleneck() and stage_effective_ms()."""
        return (sum(r["service_ms"] for r in reps) / len(reps)
                / max(1, len(reps)))

    def bottleneck(self) -> int | None:
        """The live bottleneck stage id, or None when there is no data
        OR no conclusive signal (service estimates within noise of each
        other and no queue saturated).

        Primary signal — per-stage service time: each stage's rate is
        bounded by the slowest of its three phase threads (inbound
        decode, infer, outbound encode — per-channel/per-node p50s, so
        blocking waits never pollute the estimate), divided by its
        replica count.  A clear winner (>= 1.5x the runner-up) is the
        bottleneck.  When timing is flat — e.g. a wire-bound hop whose
        cost is invisible to any CPU histogram — fall back to the
        backpressure edge: saturation propagates upstream of the
        bottleneck (full tx watermarks) while everything downstream
        starves, so the bottleneck is the most-downstream stage whose
        own rx queue watermark is saturated or whose predecessor's tx
        watermark is."""
        stages = self._stage_map()
        if not stages:
            return None
        order = sorted(stages)
        eff = {k: self._eff_ms(reps) for k, reps in stages.items()}
        top = max(eff, key=lambda k: eff[k])
        if eff[top] > 0:
            runner_up = max((v for k, v in eff.items() if k != top),
                            default=0.0)
            if len(order) == 1 or eff[top] >= 1.5 * runner_up:
                return top
        candidates = []
        for i, k in enumerate(order):
            own_rx = any(self._saturated(r, "rx") for r in stages[k])
            up_tx = i > 0 and any(self._saturated(r, "tx")
                                  for r in stages[order[i - 1]])
            if own_rx or up_tx:
                candidates.append(k)
        if candidates:
            return max(candidates)
        # neither signal is conclusive (service times within noise of
        # each other, no queue saturated): say so rather than flip
        # between near-equal stages refresh to refresh
        return None

    def _windowed_service_ms(self, node: _Node, window: int) -> float:
        """One node's window-bounded service estimate: the max of the
        three phase delta-means (see :func:`_win_mean_ms`) over the last
        ``window`` pushes.  Falls back to the lifetime p50 estimate
        when the window holds fewer than two pushes or no phase gained
        samples (an idle chain keeps its last honest figure instead of
        reading as infinitely fast)."""
        h = list(node.history)[-max(2, int(window)):]
        if not h:
            return 0.0
        if len(h) < 2:
            return _service_ms(h[-1][1])
        vals = [v for v in (_win_mean_ms(h, ph) for ph in
                            ("infer_s", "decode_s", "encode_s"))
                if v is not None]
        if not vals:
            return _service_ms(h[-1][1])
        return max(vals)

    def stage_service_ms(self, *, window: int | None = None
                         ) -> dict[int, float]:
        """Live UNDIVIDED per-stage service estimate (ms): the mean
        replica service time — what one replica costs per frame, the
        unit :func:`defer_tpu.plan.replan.measured_stage_seconds`
        expects (the solver divides by R itself).

        ``window`` bounds the estimate to the last N pushes (rolling
        delta-means) instead of the lifetime histogram fold — the form
        calibration and drift scoring use, so a long-running chain's
        current regime is scored rather than its cold-start average."""
        if window is None:
            return {k: sum(r["service_ms"] for r in reps) / len(reps)
                    for k, reps in self._stage_map().items()}
        with self._lock:
            nodes = list(self._nodes.values())
        acc: dict[int, list[float]] = {}
        for node in nodes:
            stage = node.ident.get("stage")
            if stage is None or not node.history:
                continue
            acc.setdefault(int(stage), []).append(
                self._windowed_service_ms(node, window))
        return {k: sum(vs) / len(vs) for k, vs in acc.items()}

    def stage_effective_ms(self) -> dict[int, float]:
        """Live per-stage effective service estimate (ms): the mean
        replica service time divided by the replica count — the number
        the planner's ``stage_effective_ms`` predicts."""
        return {k: self._eff_ms(reps)
                for k, reps in self._stage_map().items()}


# ---------------------------------------------------------------------------
# straggler / stall detection
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class StragglerFlag:
    stage: int
    reason: str            #: "slow" | "backpressure" | "stalled"
    measured_ms: float
    expected_ms: float
    ratio: float
    intervals: int         #: consecutive reporting intervals sustained

    def to_json(self) -> dict:
        return {"stage": self.stage, "reason": self.reason,
                "measured_ms": round(self.measured_ms, 4),
                "expected_ms": round(self.expected_ms, 4),
                "ratio": round(self.ratio, 4),
                "intervals": self.intervals}


class StragglerDetector:
    """Flags stages whose live behavior deviates from the active plan.

    ``expected_stage_ms`` is the plan's prediction (see
    :func:`expected_stage_ms`); a stage is flagged when, for the last
    ``sustain`` pushes (reporting intervals):

    * ``slow`` — its live service estimate exceeded ``factor`` × the
      plan's figure every interval;
    * ``backpressure`` — the backpressure edge pointed at it every
      interval (queue-watermark saturation, see
      :meth:`ClusterView.bottleneck`);
    * ``stalled`` — it processed nothing while some other stage did
      (a dead replica / wedged stage).

    The detector is evaluated on demand against the view's history, so
    one :meth:`observe` call at any time answers "sustained over the
    last k intervals?" without needing to be polled on a schedule.
    """

    def __init__(self, expected_ms=None, *,
                 factor: float = 1.5, sustain: int = 2):
        self.expected_ms = list(expected_ms) if expected_ms else None
        self.factor = factor
        self.sustain = max(1, sustain)
        #: (stage, reason) pairs already emitted into the flight
        #: recorder — a sustained flag is ONE event, not one per poll
        self._emitted: set[tuple[int, str]] = set()

    def _stage_history(self, view: ClusterView) -> dict[int, list[list]]:
        """stage -> per-replica push histories (newest last)."""
        out: dict[int, list[list]] = {}
        with view._lock:
            nodes = list(view._nodes.values())
        for node in nodes:
            stage = node.ident.get("stage")
            if stage is None:
                continue
            out.setdefault(int(stage), []).append(
                [p for _, p in node.history])
        return out

    def observe(self, view: ClusterView) -> list[StragglerFlag]:
        hist = self._stage_history(view)
        if not hist:
            return []
        order = sorted(hist)
        flags: dict[int, StragglerFlag] = {}
        k_sust = self.sustain

        def service_at(k: int, i_back: int) -> float:
            """Mean replica-divided service estimate i_back pushes ago."""
            reps = hist[k]
            vals = [_service_ms(h[-1 - i_back]) for h in reps
                    if len(h) > i_back]
            if not vals:
                return 0.0
            return sum(vals) / len(vals) / max(1, len(reps))

        def sat_at(k: int, i_back: int, side: str) -> bool:
            for h in hist[k]:
                if len(h) > i_back:
                    q = h[-1 - i_back].get("queues") or {}
                    depth = q.get(f"{side}_depth") or 0
                    if depth > 0 and q.get(f"{side}_hi", 0) \
                            >= SATURATION_FRAC * depth:
                        return True
            return False

        def processed_delta(k: int, n: int) -> int:
            d = 0
            for h in hist[k]:
                if len(h) > n:
                    d += (h[-1].get("processed", 0)
                          - h[-1 - n].get("processed", 0))
            return d

        enough = all(any(len(h) > k_sust for h in hist[k]) for k in order)
        for i, k in enumerate(order):
            # slow: sustained deviation from the plan's expectation
            if self.expected_ms is not None and k < len(self.expected_ms):
                exp = self.expected_ms[k]
                vals = [service_at(k, b) for b in range(k_sust)]
                if exp > 0 and vals and all(v > self.factor * exp
                                            for v in vals):
                    flags[k] = StragglerFlag(
                        stage=k, reason="slow", measured_ms=vals[0],
                        expected_ms=exp, ratio=vals[0] / exp,
                        intervals=k_sust)
            # backpressure: the saturation edge pointed at k every
            # interval (own rx saturated, or predecessor tx saturated,
            # while k's own tx stayed drained)
            if k not in flags:
                held = all(
                    (sat_at(k, b, "rx")
                     or (i > 0 and sat_at(order[i - 1], b, "tx")))
                    and not sat_at(k, b, "tx")
                    for b in range(k_sust))
                if held and any(len(h) > k_sust for h in hist[k]):
                    exp = (self.expected_ms[k]
                           if self.expected_ms is not None
                           and k < len(self.expected_ms) else 0.0)
                    meas = service_at(k, 0)
                    flags[k] = StragglerFlag(
                        stage=k, reason="backpressure", measured_ms=meas,
                        expected_ms=exp,
                        ratio=meas / exp if exp > 0 else 0.0,
                        intervals=k_sust)
            # stalled: no progress for k_sust intervals while an
            # UPSTREAM stage kept producing — work is flowing toward k
            # and k consumes none of it (a wedged/dead stage).  An
            # upstream-only condition on purpose: at a healthy stream's
            # tail the early stages finish first while later stages
            # drain, which must not read as a stall.
            if k not in flags and enough \
                    and processed_delta(k, k_sust) == 0 \
                    and any(processed_delta(j, k_sust) > 0
                            for j in order if j < k):
                flags[k] = StragglerFlag(
                    stage=k, reason="stalled", measured_ms=0.0,
                    expected_ms=0.0, ratio=0.0, intervals=k_sust)
        out = [flags[k] for k in sorted(flags)]
        live = set()
        for f in out:
            key = (f.stage, f.reason)
            live.add(key)
            if key not in self._emitted:
                self._emitted.add(key)
                emit_event("straggler", **f.to_json())
        # a flag that clears re-arms its event for the next episode
        self._emitted &= live
        return out

    def suggest(self, view: ClusterView, graph, plan, cost=None):
        """Feed the live measurements into the replanner: returns the
        :class:`~defer_tpu.plan.replan.ReplanResult` for the measured
        stage costs — the mid-stream "move the cuts / move the replicas"
        suggestion the monitor surfaces.  Uses the full per-stage
        SERVICE estimate (max of decode/infer/encode), so a straggler
        whose pain is a hop codec — invisible to infer-only latency —
        still drives the correction.  With no ``cost`` the model is
        reconstructed from the plan itself
        (:func:`~defer_tpu.plan.replan.cost_model_from_plan`), so the
        corrections are measured-vs-plan, not measured-vs-analytic."""
        from ..plan.replan import cost_model_from_plan, replan
        if cost is None:
            cost = cost_model_from_plan(graph, plan)
        # drop stages with no samples yet (a wedged-from-boot stage has
        # 0.0 service): a zero would scale that stage's cost to nothing
        # and the re-solve would pile work onto the dead stage.
        # Window-bounded on purpose: the suggestion must correct toward
        # the CURRENT regime, not the lifetime average with cold-start
        # samples folded in forever
        measured = {
            k: v / 1e3
            for k, v in view.stage_service_ms(
                window=SERVICE_WINDOW).items() if v > 0}
        result = replan(graph, plan, measured, cost)
        emit_event("replan", moved=bool(result.moved),
                   corrections={str(k): round(float(v), 4)
                                for k, v in result.corrections.items()})
        return result
