"""Flight recorder: a bounded, seq-stamped structured event ring.

Traces answer "where did this request's time go"; histograms answer
"how fast is this stage" — neither answers "WHAT HAPPENED": the shed
that bounced a tenant, the tier offer that degraded to tcp, the
straggler flag, the replan suggestion, the replica that died.  Those
are rare, structured control-plane facts, and this module is their
substrate: every process keeps one :class:`FlightRecorder` (module
singleton via :func:`recorder`), subsystems :func:`emit` events into
it, and the ring is

* **bounded** — past ``capacity`` the OLDEST event is evicted per
  append and ``events.dropped`` counts the loss (same contract as the
  tracer's span buffer);
* **seq-stamped** — a per-process monotone sequence number, so a
  consumer can prove it saw every event (gap = drop);
* **timeline-aligned** — ``t_us`` comes from the process tracer's
  anchored clock (:meth:`Tracer.now_us`), and a ``clock_adjust``
  shifts buffered events along with buffered spans, so events and
  spans interleave on ONE Perfetto-coherent axis;
* **wire-schematized** — an event is a flat JSON-safe dict
  (``{"kind", "seq", "t_us", "proc", "data"}``), shippable in an
  ``obs_push`` frame, a control reply, or a bench row, and
  :func:`validate_event` is the loud schema check both ends share.

Cluster-wide: stage nodes piggyback new events on their ``obs_push``
frames (``runtime/node.py``), answer ``{"cmd": "events_since"}``
control queries, and :class:`~defer_tpu.obs.cluster.ClusterView`
merges every process's stream into one ordered log
(``monitor --events``).  See docs/OBSERVABILITY.md for the kind table.
"""

from __future__ import annotations

import collections
import os
import threading

from .registry import REGISTRY
from .trace import register_anchor_hook, tracer

#: known event kinds -> one-line meaning (docs/OBSERVABILITY.md mirrors
#: this table).  Emitting an unknown kind raises: the schema is the
#: contract that makes a merged cluster-wide log queryable.
EVENT_KINDS = {
    "admit": "front door admitted one unit (tenant, rid)",
    "shed": "admission shed one unit (tenant, reason, predicted_ms)",
    "tier": "a hop negotiated its transport tier (hop, tier)",
    "tier_fallback": "a colocated-tier offer degraded to tcp (hop)",
    "straggler": "the detector flagged a stage (stage, reason, ratio)",
    "replan": "a replan suggestion was produced (moved, corrections)",
    "node_dead": "a watched node's push stream died (addr)",
    "watchdog": "the dispatcher watchdog fired (action, gen)",
    "stream_begin": "a data stream opened on a stage node (stage)",
    "stream_end": "a data stream drained on a stage node (stage, n)",
    "client_open": "a tenant connection said hello (tenant)",
    "client_close": "a tenant connection finished or died (tenant)",
    "decode_join": "a decode request claimed an engine slot (rid)",
    "decode_cancel": "a decode request's slot was reclaimed (rid)",
    "model_drift": "a stage's measured service drifted from the cost "
                   "model's prediction (stage, rel_err)",
    "redial": "a connect_retry attempt failed and backed off "
              "(addr, attempt, delay_ms, error)",
    "replica_lost": "a fan-in upstream connection died mid-stream "
                    "(hop, error)",
    "failover": "a replay fan-out healed a dead channel "
                "(hop, chan, addr, replayed, recovery_ms)",
    "quiesce": "a stage drained to a stable sequence point "
               "(hop, processed)",
    "cutover": "a live replan cut the chain over mid-stream "
               "(stages, quiesced)",
    "backend_lost": "the serve front door's chain backend died "
                    "(error, shed)",
    "replica_respawn": "the chain supervisor respawned a dead replica "
                       "(stage, replica, addr, rc)",
    "recompile": "XLA compiled a program after warmup — one event per "
                 "episode (count, via, label, shapes)",
    "mem_pressure": "live device-array bytes crossed the configured "
                    "threshold (bytes, threshold, live_arrays)",
    "journal": "the black-box journal spiller started or stopped "
               "(action, dir)",
    "postmortem": "a postmortem bundle was assembled "
                  "(reason, out, procs, first_fault)",
}

#: the wire schema's required keys (and the only keys)
_WIRE_KEYS = frozenset({"kind", "seq", "t_us", "proc", "data"})

#: evictions across every recorder in this process (the visible price
#: of the cap, like ``trace.dropped_spans``)
_DROPPED = REGISTRY.counter("events.dropped")


def validate_event(doc) -> dict:
    """Loudly check one wire-form event; returns it.  Both ends of the
    events plane share this — a malformed event fails at the boundary,
    not deep inside a monitor render."""
    if not isinstance(doc, dict) or set(doc) != _WIRE_KEYS:
        raise ValueError(f"event must have exactly keys "
                         f"{sorted(_WIRE_KEYS)}, got {doc!r}")
    if doc["kind"] not in EVENT_KINDS:
        raise ValueError(f"unknown event kind {doc['kind']!r}; "
                         f"known: {sorted(EVENT_KINDS)}")
    if not isinstance(doc["seq"], int) or doc["seq"] < 0:
        raise ValueError(f"event seq must be a non-negative int, "
                         f"got {doc['seq']!r}")
    if not isinstance(doc["t_us"], int):
        raise ValueError(f"event t_us must be an int, got {doc['t_us']!r}")
    if not isinstance(doc["proc"], str):
        raise ValueError(f"event proc must be a str, got {doc['proc']!r}")
    if not isinstance(doc["data"], dict):
        raise ValueError(f"event data must be a dict, got {doc['data']!r}")
    return doc


class FlightRecorder:
    """One process's bounded structured-event ring."""

    #: default ring capacity (events, not bytes); the serving burst the
    #: bench provokes fits with an order of magnitude to spare
    DEFAULT_CAPACITY = int(os.environ.get("DEFER_EVENTS_CAP",
                                          "4096") or 4096)

    def __init__(self, process: str | None = None,
                 capacity: int | None = None):
        self.process = process or f"pid{os.getpid()}"
        self.capacity = (self.DEFAULT_CAPACITY if capacity is None
                         else max(1, int(capacity)))
        self._ring: collections.deque[dict] = collections.deque()
        self._lock = threading.Lock()
        #: next seq to stamp (monotone, never reused)
        self._seq = 0
        #: events ever removed from the FRONT (drained or evicted) —
        #: the ``events_since`` cursor anchor, same contract as
        #: ``Tracer._base``
        self._base = 0
        #: events evicted because the ring was full (lifetime)
        self.dropped = 0

    # -- recording ---------------------------------------------------------

    def emit(self, kind: str, **data) -> dict:
        """Append one event (O(1) under a short lock); returns it.
        ``data`` values must be JSON-safe — they ride obs_push frames
        verbatim."""
        if kind not in EVENT_KINDS:
            raise ValueError(f"unknown event kind {kind!r}; "
                             f"known: {sorted(EVENT_KINDS)}")
        ev = {"kind": kind, "proc": self.process, "data": data}
        with self._lock:
            # t_us stamped UNDER the same lock that assigns seq, so one
            # process's seq order and timestamp order can never invert
            # (merge_events' tie-break relies on it)
            ev["t_us"] = tracer().now_us()
            ev["seq"] = self._seq
            self._seq += 1
            self._ring.append(ev)
            over = len(self._ring) - self.capacity
            for _ in range(over):
                self._ring.popleft()
                self.dropped += 1
                self._base += 1
                _DROPPED.n += 1
        return ev

    def shift_anchor(self, delta_us: int) -> None:
        """Shift buffered events by ``delta_us`` — called through the
        tracer's anchor hook when a ``clock_adjust`` lands, so events
        stay coherent with the spans they interleave with."""
        with self._lock:
            for ev in self._ring:
                ev["t_us"] += int(delta_us)

    # -- reading -----------------------------------------------------------

    def events_since(self, cursor: int, limit: int | None = None
                     ) -> tuple[int, list[dict]]:
        """(new_cursor, events emitted after ``cursor``) WITHOUT
        draining — the obs_push / ``events_since`` incremental read.
        ``limit`` caps one batch at the OLDEST N and the returned
        cursor stops after them, so a backlog paginates losslessly
        across successive reads (a newest-N cut would advance the
        cursor past events nobody ever saw, an invisible drop).  Only
        ring EVICTION loses events, and ``dropped`` counts that."""
        with self._lock:
            base = self._base
            snapshot = list(self._ring)
        start = max(0, cursor - base)
        out = snapshot[start:]
        if limit is not None and len(out) > limit:
            out = out[:limit]
        return base + start + len(out), out

    def cursor(self) -> int:
        """Monotone count of events ever emitted — pass back to
        :meth:`events_since` for an incremental batch."""
        with self._lock:
            return self._base + len(self._ring)

    def snapshot(self) -> list[dict]:
        with self._lock:
            return list(self._ring)

    def drain(self) -> list[dict]:
        with self._lock:
            out = list(self._ring)
            self._ring.clear()
            self._base += len(out)
        return out

    def clear(self) -> None:
        self.drain()
        self.dropped = 0


def merge_events(*batches) -> list[dict]:
    """Merge event batches from several processes into one ordered log:
    primary order is the clock-aligned ``t_us``, ties (and one
    process's burst inside one microsecond) break on per-process
    ``seq`` — so a single process's events can never reorder against
    each other.  ``(proc, seq)`` is a process-unique identity, so
    duplicates across batches (e.g. several in-process node reporters
    pushing one shared ring) collapse to one entry."""
    seen: set[tuple] = set()
    out = []
    for batch in batches:
        for ev in batch:
            key = (ev.get("proc"), ev.get("seq"))
            if key in seen:
                continue
            seen.add(key)
            out.append(ev)
    out.sort(key=lambda e: (e.get("t_us", 0), e.get("proc", ""),
                            e.get("seq", 0)))
    return out


#: process singleton, timeline-coupled to the process tracer
_RECORDER = FlightRecorder()
register_anchor_hook(_RECORDER.shift_anchor)


def recorder() -> FlightRecorder:
    return _RECORDER


def emit(kind: str, **data) -> dict:
    """Emit one event into the process recorder (the one-liner call
    sites use)."""
    return _RECORDER.emit(kind, **data)
