"""Log-bucketed latency histogram: mergeable, constant-time recording.

Buckets are geometric with 8 sub-buckets per power of two (~6% relative
resolution), indexed straight off ``math.frexp`` — no log() call, no
bucket-boundary search on the hot path.  Counts live in a sparse dict, so
a histogram that has only ever seen microsecond-scale pushes costs a
handful of entries, while the same type can absorb multi-second compile
outliers without preallocating thousands of buckets.

The same shape (log buckets + exact min/max/sum) is what HdrHistogram and
Prometheus native histograms use; this is the dependency-free core of it.
"""

from __future__ import annotations

import math

#: sub-buckets per octave; 8 -> bucket width ~9%, mid-point error ~6%
_SUB = 8
#: values below this clamp into the bottom bucket (1 ns for seconds data)
_FLOOR = 1e-9


def _bucket_index(v: float) -> int:
    """Bucket index of ``v`` (> 0): octave from frexp, linear sub-bucket."""
    m, e = math.frexp(v)          # v = m * 2**e, m in [0.5, 1)
    return (e << 3) | int((m - 0.5) * 16.0)


def _bucket_value(idx: int) -> float:
    """Representative (mid-point) value of bucket ``idx``."""
    e, sub = idx >> 3, idx & 7
    return math.ldexp((8 + sub + 0.5) / 16.0, e)


class LatencyHistogram:
    """Mergeable log-bucketed histogram with exact count/sum/min/max.

    ``record`` is an int increment in a dict (atomic enough under the GIL
    for the concurrent-writer case: a lost update costs one count, never a
    corrupt structure).  Quantiles interpolate inside the winning bucket,
    and are clamped to the exact observed [min, max] so p99 of a constant
    distribution is that constant.
    """

    __slots__ = ("_counts", "count", "sum", "min", "max", "__weakref__")

    def __init__(self):
        self._counts: dict[int, int] = {}
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    def record(self, value: float) -> None:
        v = float(value)
        if not math.isfinite(v):
            return
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v
        self.count += 1
        self.sum += v
        idx = _bucket_index(v if v > _FLOOR else _FLOOR)
        c = self._counts
        c[idx] = c.get(idx, 0) + 1

    def merge(self, other: "LatencyHistogram") -> "LatencyHistogram":
        """Fold ``other`` into self (e.g. per-thread or per-process shards)."""
        for idx, n in other._counts.items():
            self._counts[idx] = self._counts.get(idx, 0) + n
        self.count += other.count
        self.sum += other.sum
        self.min = min(self.min, other.min)
        self.max = max(self.max, other.max)
        return self

    def quantile(self, q: float) -> float:
        """Value at quantile ``q`` in [0, 1]; 0.0 on an empty histogram."""
        if self.count == 0:
            return 0.0
        if q <= 0:
            return self.min
        if q >= 1:
            return self.max
        target = q * self.count
        seen = 0.0
        for idx in sorted(self._counts):
            n = self._counts[idx]
            if seen + n >= target:
                # linear interpolation inside the bucket
                e, sub = idx >> 3, idx & 7
                lo = math.ldexp((8 + sub) / 16.0, e)
                hi = math.ldexp((8 + sub + 1) / 16.0, e)
                frac = (target - seen) / n
                v = lo + (hi - lo) * frac
                return min(max(v, self.min), self.max)
            seen += n
        return self.max

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0

    @property
    def percentiles(self) -> dict:
        """The headline view: p50/p95/p99/max (0.0s when empty)."""
        return {"p50": self.quantile(0.50), "p95": self.quantile(0.95),
                "p99": self.quantile(0.99),
                "max": self.max if self.count else 0.0}

    def summary(self, scale: float = 1.0, ndigits: int = 6) -> dict:
        """JSON-ready summary; ``scale`` converts units (1e3: s -> ms)."""
        if self.count == 0:
            return {"count": 0}
        r = lambda v: round(v * scale, ndigits)  # noqa: E731
        return {
            "count": self.count,
            "sum": r(self.sum),
            "mean": r(self.mean),
            "min": r(self.min),
            "p50": r(self.quantile(0.50)),
            "p95": r(self.quantile(0.95)),
            "p99": r(self.quantile(0.99)),
            "max": r(self.max),
        }

    def clear(self) -> None:
        self._counts.clear()
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    def __repr__(self):
        if self.count == 0:
            return "LatencyHistogram(empty)"
        p = self.percentiles
        return (f"LatencyHistogram(n={self.count}, p50={p['p50']:.6g}, "
                f"p99={p['p99']:.6g}, max={p['max']:.6g})")
