"""Black-box journal: crash-surviving on-disk telemetry.

Everything the observability plane knows — the flight-recorder event
ring, the metric registry, sampled spans — lives in process memory and
is only readable over a *live* control connection.  That is exactly
backwards for forensics: the more catastrophic the failure, the less
telemetry survives it.  This module is the flight-recorder's black box:
a background :class:`JournalSpiller` thread spills each process's
events, periodic registry/row snapshots, and sampled spans into an
append-only, size-bounded, crash-safe journal on disk, so a postmortem
(obs/postmortem.py) can reconstruct the fleet's last seconds from the
journals of processes that no longer exist.

Durability contract:

* **append-only segments** — each process owns one directory
  (``<root>/<proc>@<pid>/``) of numbered segment files; records are
  ``<crc32:u32><len:u32><json payload>`` so a torn final write (power
  cut, kill -9 mid-``write``) truncates cleanly at read time instead of
  poisoning the file.  Every flushed byte is in the kernel page cache —
  a SIGKILL of the process loses at most the current spill interval.
* **size-bounded ring** — segments rotate at ``segment_bytes`` and the
  OLDEST segment is deleted once the directory exceeds ``max_bytes``
  (``DEFER_JOURNAL_MAX_BYTES``), so a long-running chain journals
  forever in constant disk.
* **self-describing clock** — every segment opens with a ``meta``
  record and an ``anchor`` record pairing the tracer timeline
  (``t_us``, what events/spans are stamped with) with the host wall
  clock (``wall_us``), re-emitted whenever a ``clock_adjust`` shifts
  the tracer anchor — so post-hoc cross-process alignment needs no
  live process, only ``wall_us - t_us``.
* **measured overhead** — the spiller's own cost is first-class
  telemetry (``journal.records`` / ``journal.bytes`` counters, the
  ``journal.spill_s`` histogram) and the ``blackbox_overhead`` bench
  row asserts the end-to-end wall price stays under 5%.

See docs/OBSERVABILITY.md ("Black box & postmortem") for the record
schema and bundle layout.
"""

from __future__ import annotations

import json
import os
import re
import struct
import threading
import time
import zlib

from .registry import REGISTRY
from .trace import register_anchor_hook, tracer

#: journal format version, written in every segment's meta record; a
#: reader refuses nothing — it surfaces unknown versions as a bundle
#: warning instead (forensics must degrade, not crash)
JOURNAL_VERSION = "defer_tpu.journal.v1"

#: record framing: little-endian crc32-of-payload, payload length
_HDR = struct.Struct("<II")

#: rotate the active segment past this many bytes
DEFAULT_SEGMENT_BYTES = int(os.environ.get(
    "DEFER_JOURNAL_SEGMENT_BYTES", str(512 * 1024)) or 512 * 1024)

#: delete oldest segments once one process's journal exceeds this
DEFAULT_MAX_BYTES = int(os.environ.get(
    "DEFER_JOURNAL_MAX_BYTES", str(8 * 1024 * 1024)) or 8 * 1024 * 1024)

_SEG_RE = re.compile(r"^seg-(\d{8})$")


def _sanitize(proc: str) -> str:
    """Filesystem-safe process label (stage1.r0, serve, dispatcher)."""
    return re.sub(r"[^A-Za-z0-9._-]", "_", proc) or "proc"


class JournalWriter:
    """Append-only segment-ring writer for ONE process's journal.

    Not thread-safe by design — the single :class:`JournalSpiller`
    thread owns it; anything else that wants a record written sets a
    flag the spiller honors on its next tick."""

    def __init__(self, root: str, proc: str, *,
                 segment_bytes: int | None = None,
                 max_bytes: int | None = None,
                 pid: int | None = None):
        self.proc = proc
        self.pid = os.getpid() if pid is None else int(pid)
        self.dir = os.path.join(root, f"{_sanitize(proc)}@{self.pid}")
        self.segment_bytes = max(4096, int(segment_bytes
                                           or DEFAULT_SEGMENT_BYTES))
        self.max_bytes = max(self.segment_bytes,
                             int(max_bytes or DEFAULT_MAX_BYTES))
        os.makedirs(self.dir, exist_ok=True)
        #: lifetime spill accounting (the overhead story's raw numbers)
        self.records = 0
        self.bytes = 0
        #: segments deleted by the ring cap (evidence-gap signal: a
        #: bundle built from a capped journal must say so)
        self.segments_dropped = 0
        existing = sorted(n for name in os.listdir(self.dir)
                          if (m := _SEG_RE.match(name))
                          for n in [int(m.group(1))])
        self._seg_seq = (existing[-1] + 1) if existing else 0
        self._fh = None
        self._open_segment()

    # -- writing -----------------------------------------------------------

    def _open_segment(self) -> None:
        if self._fh is not None:
            self._fh.close()
        path = os.path.join(self.dir, f"seg-{self._seg_seq:08d}")
        self._seg_seq += 1
        self._fh = open(path, "ab")
        # every segment self-describes: a lone surviving segment is
        # still attributable and clock-alignable
        self._append({"rec": "meta", "version": JOURNAL_VERSION,
                      "proc": self.proc, "pid": self.pid})
        self.write_anchor()

    def _append(self, doc: dict) -> None:
        payload = json.dumps(doc, separators=(",", ":"),
                             default=str).encode("utf-8")
        self._fh.write(_HDR.pack(zlib.crc32(payload) & 0xFFFFFFFF,
                                 len(payload)) + payload)
        self.records += 1
        self.bytes += _HDR.size + len(payload)

    def append(self, doc: dict) -> None:
        """Write one record, rotating/capping the ring as needed."""
        self._append(doc)
        if self._fh.tell() >= self.segment_bytes:
            self._fh.flush()
            self._open_segment()
            self._enforce_cap()

    def write_anchor(self) -> None:
        """Pair the tracer timeline with the wall clock RIGHT NOW — the
        record that makes dead-process clock alignment possible."""
        self._append({"rec": "anchor",
                      "t_us": tracer().now_us(),
                      "wall_us": time.time_ns() // 1_000})

    def flush(self) -> None:
        """Push buffered bytes to the kernel (kill -9 safe; no fsync —
        surviving the process is the contract, not surviving the
        host)."""
        self._fh.flush()

    def _enforce_cap(self) -> None:
        segs = self.segments()
        total = sum(sz for _, sz in segs)
        while len(segs) > 1 and total > self.max_bytes:
            path, sz = segs.pop(0)
            try:
                os.remove(path)
            except OSError:
                break
            total -= sz
            self.segments_dropped += 1

    def segments(self) -> list[tuple[str, int]]:
        """(path, size) per live segment, oldest first."""
        out = []
        try:
            names = os.listdir(self.dir)
        except OSError:
            return out
        for name in sorted(names):
            if _SEG_RE.match(name):
                path = os.path.join(self.dir, name)
                try:
                    out.append((path, os.path.getsize(path)))
                except OSError:
                    continue
        return out

    def close(self) -> None:
        if self._fh is not None:
            self._fh.flush()
            self._fh.close()
            self._fh = None


class JournalSpiller:
    """Background thread spilling the process's obs state to a
    :class:`JournalWriter` — the :class:`~defer_tpu.obs.report.ObsReporter`
    shape (halt event + ``wait(interval)``), but the subscriber is a
    file, not a socket.

    Each tick drains flight-recorder events since the last tick
    (cursor 0 at start: boot-time events are forensics gold), the
    newest sampled spans, and — every ``snapshot_every`` ticks — one
    ``snapshot`` record from ``snapshot_fn`` (default: the metric
    registry).  A ``clock_adjust`` landing between ticks marks the
    anchor dirty; the next tick re-anchors before writing anything
    stamped with the shifted timeline."""

    def __init__(self, writer: JournalWriter, *,
                 interval_s: float = 0.25,
                 snapshot_every: int = 4,
                 snapshot_fn=None,
                 span_limit: int = 512):
        self.writer = writer
        self.interval_s = max(0.02, float(interval_s))
        self.snapshot_every = max(1, int(snapshot_every))
        self.snapshot_fn = snapshot_fn
        self.span_limit = int(span_limit)
        self._halt = threading.Event()
        self._reanchor = threading.Event()
        self._ev_cursor = 0
        self._sp_cursor = 0
        self._ticks = 0
        self._thread = threading.Thread(target=self._run,
                                        name="journal-spiller",
                                        daemon=True)
        self._spill_hist = REGISTRY.histogram("journal.spill_s")
        self._rec_ctr = REGISTRY.counter("journal.records")
        self._bytes_ctr = REGISTRY.counter("journal.bytes")
        # a clock_adjust shifts every buffered t_us; the on-disk anchor
        # must follow or post-hoc alignment silently skews.  The hook
        # list has no unregister — gate on _halt so a stopped spiller's
        # hook is a no-op, not a write into a closed file.
        register_anchor_hook(
            lambda _delta: self._halt.is_set() or self._reanchor.set())

    def start(self) -> "JournalSpiller":
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._halt.wait(self.interval_s):
            try:
                self._tick()
            except Exception:  # noqa: BLE001 — the journal must never
                # take down the process it exists to explain
                pass

    def _tick(self, final: bool = False) -> None:
        from .events import recorder
        t0 = time.perf_counter()
        w = self.writer
        before = w.bytes
        if self._reanchor.is_set():
            self._reanchor.clear()
            w.write_anchor()
        rec = recorder()
        self._ev_cursor, evs = rec.events_since(self._ev_cursor)
        now = tracer().now_us()
        if evs:
            w.append({"rec": "events", "t_us": now, "events": evs,
                      "dropped": rec.dropped})
        tr = tracer()
        if tr.enabled:
            self._sp_cursor, spans = tr.spans_since(
                self._sp_cursor, limit=self.span_limit)
            if spans:
                w.append({"rec": "spans", "t_us": now, "spans": spans,
                          "dropped": tr.dropped})
        self._ticks += 1
        if self.snapshot_fn is not None and (
                final or self._ticks % self.snapshot_every == 1):
            try:
                payload = self.snapshot_fn()
            except Exception as e:  # noqa: BLE001 — a dying node's
                # snapshot hook may find half-torn state; record that
                payload = {"snapshot_error": repr(e)}
            w.append({"rec": "snapshot", "t_us": tracer().now_us(),
                      "payload": payload})
        w.flush()
        dt = time.perf_counter() - t0
        self._spill_hist.record(dt)
        self._rec_ctr.n = w.records
        self._bytes_ctr.n = w.bytes

    def stop(self) -> None:
        """Final spill (anchor + whatever accumulated), then close."""
        if self._halt.is_set():
            return
        self._halt.set()
        self._thread.join(timeout=5.0)
        try:
            self._tick(final=True)
            self.writer.write_anchor()
            self.writer.flush()
        except Exception:  # noqa: BLE001 — shutdown is best-effort
            pass
        self.writer.close()


# -- process singleton --------------------------------------------------

_ACTIVE: JournalSpiller | None = None
_ACTIVE_LOCK = threading.Lock()


def start_journal(root: str, proc: str | None = None, *,
                  snapshot_fn=None, interval_s: float = 0.25,
                  snapshot_every: int = 4,
                  segment_bytes: int | None = None,
                  max_bytes: int | None = None) -> JournalSpiller:
    """Start (or replace) THIS process's journal under ``root``.

    ``proc`` defaults to the process tracer's label so journal
    directories, span ``proc`` fields, and event ``proc`` fields all
    agree — the postmortem merger keys on that."""
    global _ACTIVE
    if snapshot_fn is None:
        snapshot_fn = lambda: {"registry": REGISTRY.snapshot()}  # noqa: E731
    with _ACTIVE_LOCK:
        if _ACTIVE is not None:
            _ACTIVE.stop()
        writer = JournalWriter(root, proc or tracer().process,
                               segment_bytes=segment_bytes,
                               max_bytes=max_bytes)
        _ACTIVE = JournalSpiller(writer, interval_s=interval_s,
                                 snapshot_every=snapshot_every,
                                 snapshot_fn=snapshot_fn).start()
    from .events import emit
    emit("journal", action="start", dir=writer.dir)
    return _ACTIVE


def stop_journal() -> None:
    """Stop the process journal after one final spill (idempotent)."""
    global _ACTIVE
    with _ACTIVE_LOCK:
        sp, _ACTIVE = _ACTIVE, None
    if sp is not None:
        try:
            from .events import emit
            emit("journal", action="stop", dir=sp.writer.dir)
        except Exception:  # noqa: BLE001 — stop must stay infallible
            pass
        sp.stop()


def active_journal() -> JournalSpiller | None:
    return _ACTIVE


# -- reading (the postmortem side; works on dead processes) -------------

def read_segment(path: str) -> tuple[list[dict], bool]:
    """(records, truncated): parse one segment, STOPPING at the first
    torn record — short header, short payload, or CRC mismatch — and
    reporting it.  Everything before the tear is intact by
    construction (records are written whole, in order)."""
    records: list[dict] = []
    truncated = False
    try:
        with open(path, "rb") as fh:
            data = fh.read()
    except OSError:
        return records, True
    off = 0
    n = len(data)
    while off < n:
        if off + _HDR.size > n:
            truncated = True
            break
        crc, ln = _HDR.unpack_from(data, off)
        payload = data[off + _HDR.size: off + _HDR.size + ln]
        if len(payload) < ln or zlib.crc32(payload) & 0xFFFFFFFF != crc:
            truncated = True
            break
        try:
            records.append(json.loads(payload.decode("utf-8")))
        except ValueError:
            truncated = True
            break
        off += _HDR.size + ln
    return records, truncated


def read_journal(proc_dir: str) -> dict:
    """One dead-or-alive process's journal, segments stitched oldest
    first: ``{proc, pid, version, records, segments, truncated,
    warnings}``.  Never raises on bad input — forensics on a torn
    directory must yield a partial story, not a stack trace."""
    base = os.path.basename(proc_dir.rstrip("/"))
    proc, _, pid = base.rpartition("@")
    doc = {"proc": proc or base, "pid": int(pid) if pid.isdigit() else None,
           "version": None, "records": [], "segments": 0,
           "truncated": False, "warnings": []}
    segs = []
    try:
        segs = sorted(name for name in os.listdir(proc_dir)
                      if _SEG_RE.match(name))
    except OSError as e:
        doc["warnings"].append(f"unreadable journal dir {proc_dir}: {e}")
        return doc
    if not segs:
        doc["warnings"].append(f"journal dir {proc_dir} has no segments")
        return doc
    for i, name in enumerate(segs):
        records, truncated = read_segment(os.path.join(proc_dir, name))
        # only the FINAL segment may legitimately end torn (the write
        # the crash interrupted); a tear mid-ring means lost evidence
        if truncated:
            doc["truncated"] = True
            if i != len(segs) - 1:
                doc["warnings"].append(
                    f"segment {name} torn mid-ring (not the final "
                    f"segment) — records after the tear are lost")
        for r in records:
            if r.get("rec") == "meta":
                doc["version"] = r.get("version", doc["version"])
                if r.get("proc"):
                    doc["proc"] = r["proc"]
                if r.get("pid") is not None:
                    doc["pid"] = r["pid"]
        doc["records"].extend(records)
        doc["segments"] += 1
    if doc["version"] not in (None, JOURNAL_VERSION):
        doc["warnings"].append(
            f"journal version {doc['version']!r} != reader's "
            f"{JOURNAL_VERSION!r} — best-effort parse")
    if doc["version"] is None:
        doc["warnings"].append(
            f"no meta record in {proc_dir} — unversioned journal")
    return doc


def read_process_journals(root: str) -> list[dict]:
    """Every per-process journal under ``root`` (see
    :func:`read_journal`); an empty or missing root returns ``[]`` —
    the caller turns that into a loud partial-bundle warning."""
    out = []
    try:
        names = sorted(os.listdir(root))
    except OSError:
        return out
    for name in names:
        path = os.path.join(root, name)
        if os.path.isdir(path) and "@" in name:
            out.append(read_journal(path))
    return out
