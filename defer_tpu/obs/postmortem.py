"""Postmortem collector: one bundle from the fleet's black boxes.

The journal (obs/journal.py) makes each process's telemetry survive
that process; this module makes the *fleet's* failure explainable.
:func:`collect` gathers every per-process journal under a
``--journal-dir`` — including (especially) the dead ones — aligns them
onto one wall-clock axis using their anchor records, and emits a
bundle directory:

* ``bundle.json`` — merged cross-process event timeline, last-known
  ClusterView-style row per process, per-process journal lifetimes,
  loud ``warnings`` (missing journals, torn segments, dropped-event
  evidence gaps), and the first-fault **verdict**;
* ``trace.json`` — a Perfetto/Chrome trace of the last ``last_s``
  seconds: every journaled span plus every event as an instant marker,
  all processes on one aligned timeline.

The verdict walks the aligned evidence backwards from the failure,
exactly the way a human would (docs/OBSERVABILITY.md):

1. **who died first** — the process whose journal stops earliest,
   measurably before the survivors kept writing;
2. **who said so** — the first fatal event on the merged timeline
   (``watchdog dead``, ``node_dead``, ``backend_lost``,
   ``replica_lost``, ``failover``, ``replica_respawn``), which also
   names the victim when the supervisor respawned it;
3. **who backed up** — survivors whose upstream queue watermarks
   saturated in their final snapshot are casualties of the stall, not
   causes, and are ordered downstream of the victim.

:func:`maybe_autopsy` is the in-crisis entry point: failure paths
(``run_chain`` teardown, the failover supervisor, the dispatcher
watchdog, the serve front door's backend loss) call it fire-and-forget;
it assembles a bundle on a daemon thread, rate-limited per process,
and can never make the failure worse.
"""

from __future__ import annotations

import json
import os
import threading
import time

from .events import merge_events
from .journal import JOURNAL_VERSION, active_journal, read_process_journals

#: bundle format version (bundle.json carries it)
BUNDLE_VERSION = "defer_tpu.postmortem.v1"

#: event kinds that are failure evidence, not routine telemetry
FATAL_KINDS = ("node_dead", "backend_lost", "replica_lost",
               "failover", "replica_respawn", "watchdog")

#: a queue watermark at >= this fraction of its depth in a process's
#: final snapshot reads as "backed up behind the fault" (the
#: ClusterView saturation convention)
SATURATION_FRAC = 0.9

#: a journal that stops this much before the latest-writing survivor
#: is an early stopper (must comfortably exceed the spill interval)
STALL_MARGIN_US = 1_000_000


def _is_fatal(ev: dict) -> bool:
    kind = ev.get("kind")
    if kind == "watchdog":
        return (ev.get("data") or {}).get("action") == "dead"
    return kind in FATAL_KINDS


def _victim_of(ev: dict) -> str | None:
    """The process label a fatal event names, where it names one."""
    data = ev.get("data") or {}
    kind = ev.get("kind")
    if kind == "replica_respawn" and data.get("stage") is not None:
        label = f"stage{data['stage']}"
        if data.get("replica") is not None:
            label += f".r{data['replica']}"
        return label
    if kind in ("node_dead",) and data.get("addr"):
        return str(data["addr"])
    return None


def _stage_index(proc: str) -> int | None:
    if proc.startswith("stage"):
        digits = ""
        for ch in proc[5:]:
            if ch.isdigit():
                digits += ch
            else:
                break
        if digits:
            return int(digits)
    return None


def _align(journal: dict) -> dict:
    """Shift one journal's records onto the wall-clock axis using its
    LAST anchor (the most recent clock correction wins), returning the
    digested per-process view the bundle uses."""
    anchors = [r for r in journal["records"] if r.get("rec") == "anchor"
               and isinstance(r.get("t_us"), int)
               and isinstance(r.get("wall_us"), int)]
    delta = (anchors[-1]["wall_us"] - anchors[-1]["t_us"]) if anchors else 0
    events: list[dict] = []
    spans: list[dict] = []
    dropped = 0
    snapshot = None
    snapshot_us = None
    lo = hi = None
    for r in journal["records"]:
        t = r.get("t_us")
        if isinstance(t, int):
            t += delta
            lo = t if lo is None else min(lo, t)
            hi = t if hi is None else max(hi, t)
        kind = r.get("rec")
        if kind == "events":
            dropped = max(dropped, int(r.get("dropped", 0) or 0))
            for ev in r.get("events") or []:
                ev = dict(ev)
                if isinstance(ev.get("t_us"), int):
                    ev["t_us"] += delta
                events.append(ev)
        elif kind == "spans":
            for s in r.get("spans") or []:
                s = dict(s)
                if isinstance(s.get("ts_us"), int):
                    s["ts_us"] += delta
                spans.append(s)
        elif kind == "snapshot":
            snapshot = r.get("payload")
            snapshot_us = t
    warnings = list(journal.get("warnings") or [])
    if not anchors:
        warnings.append(
            f"{journal['proc']}: no clock-anchor record — timeline "
            f"left on its raw tracer axis (alignment unverified)")
    return {"proc": journal["proc"], "pid": journal.get("pid"),
            "version": journal.get("version"), "delta_us": delta,
            "events": events, "spans": spans,
            "events_dropped": dropped,
            "snapshot": snapshot, "snapshot_us": snapshot_us,
            "first_us": lo, "last_us": hi,
            "truncated": bool(journal.get("truncated")),
            "segments": journal.get("segments", 0),
            "warnings": warnings}


def _saturated(snapshot: dict | None) -> list[str]:
    """Queue watermarks at/over SATURATION_FRAC of depth in a final
    snapshot — the 'backed up behind the fault' signal."""
    out = []
    q = (snapshot or {}).get("queues") or {}
    for side in ("rx", "tx"):
        depth = q.get(f"{side}_depth") or 0
        hi = q.get(f"{side}_hi") or 0
        if depth and hi >= SATURATION_FRAC * depth:
            out.append(f"{side} watermark {hi}/{depth}")
    return out


def _verdict(procs: list[dict], timeline: list[dict],
             reason: str | None) -> dict:
    """First-fault localization over the aligned evidence (see module
    docstring for the heuristics, in precedence order)."""
    evidence: list[str] = []
    last_writers = [p for p in procs if p["last_us"] is not None]
    global_last = max((p["last_us"] for p in last_writers), default=None)
    stoppers = sorted((p for p in last_writers
                       if global_last is not None
                       and p["last_us"] <= global_last - STALL_MARGIN_US),
                      key=lambda p: p["last_us"])
    fatal = next((ev for ev in timeline if _is_fatal(ev)), None)
    named = _victim_of(fatal) if fatal else None

    first_fault = None
    if stoppers:
        first_fault = stoppers[0]["proc"]
        evidence.append(
            f"journal of {first_fault} stops at "
            f"{stoppers[0]['last_us']} us, "
            f"{(global_last - stoppers[0]['last_us']) / 1e6:.2f}s before "
            f"the last surviving writer")
    if fatal is not None:
        evidence.append(
            f"first fatal event: {fatal['kind']} from {fatal['proc']} "
            f"at {fatal['t_us']} us {fatal.get('data')!r}")
        if named and first_fault is None:
            first_fault = named
        elif named and named != first_fault and \
                not str(first_fault).startswith(named):
            evidence.append(f"event names {named} (journal-stop and "
                            f"event evidence disagree)")
    if first_fault is None and reason:
        evidence.append(f"no early-stopped journal and no fatal event; "
                        f"collector reason: {reason}")

    casualties: list[dict] = []
    if first_fault is not None:
        victim_stage = _stage_index(first_fault)
        ranked = []
        for p in procs:
            if p["proc"] == first_fault:
                continue
            why = _saturated(p["snapshot"])
            stage = _stage_index(p["proc"])
            if stage is not None and victim_stage is not None:
                # downstream of the victim starves, upstream backs up;
                # order casualties downstream-first, nearest first
                order = (0, stage - victim_stage) \
                    if stage > victim_stage else (1, victim_stage - stage)
                role = ("downstream" if stage > victim_stage
                        else "upstream" if stage < victim_stage
                        else "peer replica")
            else:
                order, role = (2, 0), "control plane"
            if why or role != "control plane":
                ranked.append((order, {"proc": p["proc"], "role": role,
                                       "saturated": why}))
        ranked.sort(key=lambda t: t[0])
        casualties = [c for _, c in ranked]

    return {"first_fault": first_fault,
            "fatal_event": fatal,
            "evidence": evidence,
            "casualties": casualties,
            "reason": reason}


def _chrome_trace(procs: list[dict], cut_us: int | None) -> dict:
    """Perfetto view of the bundle's last window: journaled spans as
    complete events, flight-recorder events as instant markers."""
    pids: dict[str, int] = {}
    out: list[dict] = []

    def pid_of(proc: str) -> int:
        return pids.setdefault(proc, len(pids) + 1)

    for p in procs:
        for s in p["spans"]:
            ts = s.get("ts_us", 0)
            if cut_us is not None and ts + s.get("dur_us", 0) < cut_us:
                continue
            out.append({"name": s.get("name", "?"), "ph": "X",
                        "ts": ts, "dur": s.get("dur_us", 1),
                        "pid": pid_of(s.get("proc", p["proc"])),
                        "tid": s.get("tid", 0),
                        "cat": "span", "args": s.get("args") or {}})
        for ev in p["events"]:
            ts = ev.get("t_us", 0)
            if cut_us is not None and ts < cut_us:
                continue
            out.append({"name": ev.get("kind", "?"), "ph": "i",
                        "ts": ts, "pid": pid_of(ev.get("proc", p["proc"])),
                        "tid": 0, "s": "p", "cat": "event",
                        "args": ev.get("data") or {}})
    for proc, pid in pids.items():
        out.append({"name": "process_name", "ph": "M", "pid": pid,
                    "args": {"name": proc}})
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def collect(journal_dir: str, *, out_dir: str | None = None,
            reason: str | None = None, last_s: float = 30.0) -> dict:
    """Assemble one postmortem bundle from the journals under
    ``journal_dir`` — dead processes welcome; no live control
    connection is used or needed.  Returns the bundle document (also
    written to ``<out_dir>/bundle.json`` + ``trace.json``).  Missing
    or empty journal dirs yield a loud partial bundle, never a
    crash."""
    journals = read_process_journals(journal_dir)
    procs = [_align(j) for j in journals]
    warnings: list[str] = []
    if not procs:
        warnings.append(
            f"PARTIAL BUNDLE: no journals found under {journal_dir!r} — "
            f"was the chain started with --journal-dir?")
    for p in procs:
        warnings.extend(p["warnings"])
        if p["truncated"]:
            warnings.append(
                f"{p['proc']}: final record torn mid-write (crash "
                f"artifact) — truncated at the tear, earlier records "
                f"intact")

    timeline = merge_events(*[p["events"] for p in procs])
    events_dropped = sum(p["events_dropped"] for p in procs)
    if events_dropped:
        # satellite: a bundle from rings that dropped records must
        # scream about the gap, not present a silently thinned timeline
        warnings.append(
            f"EVIDENCE GAP: {events_dropped} flight-recorder events "
            f"were dropped by ring eviction before journaling — the "
            f"timeline has holes (raise DEFER_EVENTS_CAP or shorten "
            f"the spill interval)")

    last_all = [p["last_us"] for p in procs if p["last_us"] is not None]
    cut_us = (max(last_all) - int(last_s * 1e6)) if last_all else None
    verdict = _verdict(procs, timeline, reason)
    verdict["events_dropped"] = events_dropped

    bundle = {
        "version": BUNDLE_VERSION,
        "journal_version": JOURNAL_VERSION,
        "journal_dir": journal_dir,
        "reason": reason,
        "warnings": warnings,
        "events_dropped": events_dropped,
        "procs": [{k: p[k] for k in
                   ("proc", "pid", "version", "delta_us", "first_us",
                    "last_us", "events_dropped", "truncated", "segments")}
                  for p in procs],
        "rows": {p["proc"]: p["snapshot"] for p in procs
                 if p["snapshot"] is not None},
        "timeline": timeline,
        "verdict": verdict,
    }
    if out_dir is None:
        stamp = time.strftime("%Y%m%d-%H%M%S")
        out_dir = os.path.join(journal_dir,
                               f"bundle-{stamp}-pid{os.getpid()}")
    os.makedirs(out_dir, exist_ok=True)
    bundle["out_dir"] = out_dir
    with open(os.path.join(out_dir, "bundle.json"), "w") as fh:
        json.dump(bundle, fh, indent=1, default=str)
    with open(os.path.join(out_dir, "trace.json"), "w") as fh:
        json.dump(_chrome_trace(procs, cut_us), fh, default=str)
    return bundle


# -- in-crisis entry point ----------------------------------------------

_AUTOPSY_LOCK = threading.Lock()
_LAST_AUTOPSY = 0.0


def maybe_autopsy(reason: str, *, journal_dir: str | None = None,
                  min_interval_s: float = 10.0,
                  sync: bool = False,
                  delay_s: float = 0.75) -> threading.Thread | None:
    """Fire-and-forget bundle assembly from a failure path.

    No-op unless this process is journaling (or an explicit
    ``journal_dir`` is given); rate-limited so a failover storm emits
    one bundle per episode, not one per casualty.  Runs on a daemon
    thread by default — a teardown path must not block on forensics —
    and swallows everything: the autopsy can never worsen the crash.
    ``delay_s`` lets the spillers flush the failure's own events
    (e.g. ``replica_respawn``) to disk before the bundle reads it."""
    global _LAST_AUTOPSY
    if journal_dir is None:
        sp = active_journal()
        if sp is None:
            return None
        journal_dir = os.path.dirname(sp.writer.dir)
    with _AUTOPSY_LOCK:
        now = time.monotonic()
        if now - _LAST_AUTOPSY < min_interval_s:
            return None
        _LAST_AUTOPSY = now

    def _run():
        try:
            if delay_s > 0:
                time.sleep(delay_s)
            bundle = collect(journal_dir, reason=reason)
            from .events import emit
            emit("postmortem", reason=reason, out=bundle["out_dir"],
                 procs=len(bundle["procs"]),
                 first_fault=(bundle["verdict"] or {}).get("first_fault"))
            print(f"postmortem: bundle at {bundle['out_dir']} "
                  f"(reason: {reason})", flush=True)
        except Exception:  # noqa: BLE001 — forensics must not re-crash
            pass

    if sync:
        _run()
        return None
    t = threading.Thread(target=_run, name="postmortem", daemon=True)
    t.start()
    return t
