"""Stage-interior profiling plane (docs/OBSERVABILITY.md §Profiling).

Three instruments that compose with the live observability plane
instead of replacing it:

* **Phase decomposition** — the compute loops split each frame's
  opaque ``infer`` interval into named phases (``dispatch``: the jit
  call returning, ``device``: ``block_until_ready``, ``host_sync``:
  ``np.asarray``); this module owns the phase NAME table and the
  session arithmetic over the per-node histograms the loops feed.
* **Recompile telemetry** — :class:`RecompileWatcher` hooks
  ``jax.monitoring``'s ``backend_compile_duration`` stream (with a
  :meth:`~RecompileWatcher.wrap` shape-signature fallback for callables
  that bypass jit, or for builds without the monitoring events) to
  count XLA compilations per process and emit ONE ``recompile``
  flight-recorder event per compile episode — the same
  emit-once/re-arm discipline as ``model_drift``, so a recompile storm
  is one log line per burst, not thousands.
* **Memory telemetry** — :func:`device_memory_bytes` prices the live
  device arrays (``jax.live_arrays``) without importing jax into a
  process that never used it; :class:`MemoryWatcher` turns it into the
  ``device.mem_bytes`` gauge plus a thresholded ``mem_pressure`` event
  (hysteresis re-arm at 90% of the threshold).

:class:`ProfileSession` is the on-demand half: a node's
``profile_start``/``profile_stop`` control commands bracket a window
and reply with the DELTA phase breakdown (counts and summed seconds
per phase over exactly that window), the recompiles inside it, and the
live-memory reading — the machine-readable row the ``defer_tpu
profile`` CLI merges across nodes.  Everything here is off until
asked for: the watchers are installed lazily and the phase histograms
are the same always-on-cheap instruments the stats plane already pays
for.
"""

from __future__ import annotations

import os
import sys
import threading
import time

from .events import emit as emit_event
from .registry import REGISTRY

#: the named phases of one frame through a stage node's compute loop,
#: in wall order.  ``dispatch`` + ``queue`` + ``device`` + ``host_sync``
#: tiles ``infer``, which stays the issue-to-materialize total — the
#: invariant ``scripts/profile_smoke.py`` asserts.  ``queue`` is the
#: frame's residency in the async in-flight window between its dispatch
#: returning and its drain turn: ~0 in the serial loop, and in the
#: overlapped loop the latency the pipeline HIDES (a large queue share
#: on a fast stage is overlap working, not time lost).
NODE_PHASES = ("dispatch", "queue", "device", "host_sync")

#: the decode engine's per-step phases (serve/engine.py): host-side
#: gather of the per-slot rows, jit dispatch, device wait, host sync of
#: the sampled ids, and per-slot delivery/bookkeeping.  Sampling and
#: the KV write happen INSIDE the fused step program, so they are part
#: of ``device`` here; splitting them needs ``jax.profiler`` (the
#: profile CLI's --jax-trace), not host timers.
ENGINE_PHASES = ("gather", "dispatch", "device", "sync", "delivery")

#: the jax.monitoring duration event that fires once per XLA backend
#: compilation (and never on a program-cache hit)
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


def _fmt_shapes(args) -> list[str]:
    """``f32[8,128]``-style abstract shapes for event payloads (arrays
    only; scalars/pytrees are summarized by type name)."""
    out = []
    for a in args:
        shape = getattr(a, "shape", None)
        dtype = getattr(a, "dtype", None)
        if shape is not None and dtype is not None:
            out.append(f"{dtype}[{','.join(str(s) for s in shape)}]")
        else:
            out.append(type(a).__name__)
    return out


class RecompileWatcher:
    """Counts XLA compilations in this process and emits ONE
    ``recompile`` flight-recorder event per compile EPISODE.

    An episode is a burst of compiles separated from the previous burst
    by at least ``episode_gap_s`` of quiet: the first compile of a
    burst emits (carrying the via/label/shape attribution), the rest
    only count — so an injected shape change on a hot loop produces
    exactly one event, and warmup compiles before :meth:`arm` produce
    none.  Counting is always on once installed; event emission starts
    at :meth:`arm` (call it after warmup, or never for a silent
    counter).
    """

    def __init__(self, *, episode_gap_s: float = 5.0):
        self.episode_gap_s = float(episode_gap_s)
        self._lock = threading.Lock()
        self._installed = False
        self._armed = False
        self._last_t: float | None = None
        self._compiles = REGISTRY.counter("jax.compiles")
        self._compile_s = REGISTRY.histogram("jax.compile_s")

    @property
    def count(self) -> int:
        return self._compiles.value

    def install(self) -> "RecompileWatcher":
        """Register the ``jax.monitoring`` listener (idempotent; a
        process that never imports jax can still :meth:`wrap`)."""
        with self._lock:
            if self._installed:
                return self
            try:
                import jax.monitoring as _mon
                _mon.register_event_duration_secs_listener(
                    self._on_duration)
            except Exception as e:  # noqa: BLE001 — builds without the
                # monitoring events fall back to wrap(); counting just
                # loses the listener path, loudly on stderr once
                print(f"profile: jax.monitoring unavailable ({e!r}); "
                      f"recompile counting rides wrap() only",
                      file=sys.stderr, flush=True)
            self._installed = True
            return self

    def arm(self) -> None:
        """Start (or restart) event emission: the NEXT compile opens a
        fresh episode and emits.  Call after warmup."""
        with self._lock:
            self._armed = True
            self._last_t = None

    def disarm(self) -> None:
        """Stop event emission (counting continues — it is always on
        once installed).  A later :meth:`arm` restarts episodes."""
        with self._lock:
            self._armed = False

    # -- the two ingestion paths -------------------------------------------

    def _on_duration(self, name: str, dur: float, **kw) -> None:
        if name != _COMPILE_EVENT:
            return
        self._record(dur, via="jax.monitoring", label=None, shapes=None)

    def wrap(self, fn, label: str = ""):
        """Shape-signature fallback: returns ``fn`` wrapped so a call
        whose array signature (shape+dtype per argument) was never seen
        before is recorded as a compilation — what a jitted callable
        would do — with the abstract shapes attached to the event.
        Use when ``jax.monitoring`` is unavailable, or to attribute
        recompiles to a specific call site by ``label``."""
        seen: set = set()
        lock = threading.Lock()

        def wrapped(*args, **kwargs):
            sig = tuple(_fmt_shapes(args))
            with lock:
                fresh = sig not in seen
                if fresh:
                    seen.add(sig)
            if fresh:
                self._record(0.0, via="wrap", label=label,
                             shapes=list(sig))
            return fn(*args, **kwargs)

        wrapped.__wrapped__ = fn
        return wrapped

    def _record(self, dur: float, *, via, label, shapes) -> None:
        self._compiles.inc()
        if dur:
            self._compile_s.record(dur)
        now = time.monotonic()
        with self._lock:
            quiet = (self._last_t is None
                     or now - self._last_t >= self.episode_gap_s)
            self._last_t = now
            # episode discipline: only the first compile after
            # episode_gap_s of quiet emits; the rest of the burst just
            # counts (re-arming is lazy — no timer thread)
            fire = self._armed and quiet
        if fire:
            data = {"count": self._compiles.value, "via": via}
            if label:
                data["label"] = label
            if shapes:
                data["shapes"] = shapes
            emit_event("recompile", **data)


def device_memory(ensure: bool = False) -> tuple[int, int] | None:
    """(total bytes, array count) of this process's live device arrays
    — ``None`` when jax was never imported here (``ensure=True`` forces
    the import) or the backend has no ``live_arrays``.  Cheap enough
    for the obs_push cadence, not for the per-frame hot path."""
    if "jax" not in sys.modules and not ensure:
        return None
    import jax
    try:
        arrs = jax.live_arrays()
    except Exception:  # noqa: BLE001 — backend without live_arrays
        return None
    total = 0
    for a in arrs:
        try:
            total += int(a.nbytes)
        except Exception:  # noqa: BLE001 — deleted/donated buffers
            pass
    return total, len(arrs)


def device_memory_bytes(ensure: bool = False) -> int | None:
    mem = device_memory(ensure)
    return None if mem is None else mem[0]


class MemoryWatcher:
    """Publishes live device-array bytes as the ``device.mem_bytes``
    gauge and emits a ``mem_pressure`` event when a threshold is
    crossed (one per excursion: re-arms below 90% of the threshold).

    The threshold, first match wins: :meth:`set_threshold`, the
    ``DEFER_MEM_PRESSURE_BYTES`` env var (absolute bytes — the testable
    knob on backends without memory_stats), or
    ``DEFER_MEM_PRESSURE_FRAC`` (default 0.9) of the device's
    ``memory_stats()['bytes_limit']`` where the backend reports one.
    No threshold -> gauge only, no events.
    """

    def __init__(self):
        self._threshold: float | None = None
        self._armed = True
        self._gauge = REGISTRY.gauge("device.mem_bytes")

    def set_threshold(self, n_bytes: float | None) -> None:
        self._threshold = None if n_bytes is None else float(n_bytes)

    def threshold_bytes(self) -> float | None:
        if self._threshold is not None:
            return self._threshold
        env = os.environ.get("DEFER_MEM_PRESSURE_BYTES")
        if env:
            return float(env)
        if "jax" not in sys.modules:
            return None
        import jax
        try:
            stats = jax.devices()[0].memory_stats() or {}
        except Exception:  # noqa: BLE001 — cpu backend: no stats
            return None
        limit = stats.get("bytes_limit")
        if not limit:
            return None
        frac = float(os.environ.get("DEFER_MEM_PRESSURE_FRAC", "0.9"))
        return limit * frac

    def observe(self) -> int | None:
        """One reading: update the gauge, check the threshold.  Called
        from obs_snapshot (per push), never per frame."""
        mem = device_memory()
        if mem is None:
            return None
        n, arrs = mem
        self._gauge.v = float(n)
        thr = self.threshold_bytes()
        if thr:
            if self._armed and n > thr:
                self._armed = False
                emit_event("mem_pressure", bytes=n,
                           threshold=int(thr), live_arrays=arrs)
            elif not self._armed and n < 0.9 * thr:
                self._armed = True
        return n


class ProfileSession:
    """One ``profile_start`` .. ``profile_stop`` window on a node: a
    baseline snapshot of the phase histograms at start, a delta
    breakdown at stop.

    The phase histograms are cumulative (they feed stats/obs_push for
    the process lifetime); the session subtracts its start snapshot so
    the reply prices exactly the profiled window.  Window percentiles
    are not derivable from two cumulative snapshots — the reply carries
    per-phase ``count``/``sum_s``/``mean_ms`` (exact over the window)
    and the cumulative p50 for context."""

    def __init__(self, hists: dict, *, processed=None,
                 jax_trace_dir: str | None = None):
        #: name -> LatencyHistogram | None (absent phases stay None)
        self._hists = dict(hists)
        self._processed = processed  # callable -> int, or None
        self._jax_trace_dir = jax_trace_dir
        self._jax_tracing = False
        self._t0: float | None = None
        self._base: dict | None = None

    @staticmethod
    def _snap(h) -> tuple[int, float]:
        if h is None:
            return 0, 0.0
        s = h.summary()
        return int(s.get("count", 0)), float(s.get("sum", 0.0))

    def start(self) -> dict:
        if self._t0 is not None:
            raise RuntimeError("profile session already started")
        watcher = recompile_watcher().install()
        self._base = {name: self._snap(h)
                      for name, h in self._hists.items()}
        self._base_compiles = watcher.count
        self._base_processed = (self._processed()
                                if self._processed else 0)
        self._t0 = time.perf_counter()
        if self._jax_trace_dir:
            try:
                import jax
                jax.profiler.start_trace(self._jax_trace_dir)
                self._jax_tracing = True
            except Exception as e:  # noqa: BLE001 — backend without a
                # profiler must not fail the session; the phase
                # breakdown still answers
                print(f"profile: jax.profiler.trace unavailable "
                      f"({e!r})", file=sys.stderr, flush=True)
        return {"t0_unix": time.time()}

    def stop(self) -> dict:
        if self._t0 is None:
            raise RuntimeError("profile session never started")
        dt = time.perf_counter() - self._t0
        if self._jax_tracing:
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception as e:  # noqa: BLE001 — symmetric guard
                print(f"profile: stop_trace failed ({e!r})",
                      file=sys.stderr, flush=True)
        watcher = recompile_watcher()
        phases = {}
        for name, h in self._hists.items():
            c1, s1 = self._snap(h)
            c0, s0 = self._base[name]
            dc, ds = c1 - c0, s1 - s0
            phases[name] = {
                "count": dc,
                "sum_s": round(ds, 6),
                "mean_ms": round(ds / dc * 1e3, 4) if dc else None,
                "p50_ms_cum": (round(float(h.summary().get(
                    "p50", 0.0)) * 1e3, 4) if h is not None else None),
            }
        doc = {
            "duration_s": round(dt, 6),
            "phases": phases,
            "recompiles": watcher.count - self._base_compiles,
            "mem_bytes": device_memory_bytes(),
            "jax_trace_dir": (self._jax_trace_dir
                              if self._jax_tracing else None),
        }
        if self._processed is not None:
            doc["processed"] = (self._processed()
                                - self._base_processed)
        self._t0 = None
        return doc


_WATCHER: RecompileWatcher | None = None
_MEM: MemoryWatcher | None = None
_LOCK = threading.Lock()


def recompile_watcher() -> RecompileWatcher:
    """This process's recompile watcher (NOT auto-installed: call
    ``.install()`` to hook jax.monitoring)."""
    global _WATCHER
    with _LOCK:
        if _WATCHER is None:
            _WATCHER = RecompileWatcher()
        return _WATCHER


def memory_watcher() -> MemoryWatcher:
    global _MEM
    with _LOCK:
        if _MEM is None:
            _MEM = MemoryWatcher()
        return _MEM
