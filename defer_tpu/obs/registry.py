"""Process-wide metrics registry: named counters, gauges, histograms.

One registry per process (module-level :data:`REGISTRY`); instruments are
created once by name and then held by the instrumented code as plain
attributes — the hot path never goes through the registry dict.  Snapshots
are pull-based: ``snapshot()`` returns a JSON-ready dict, ``exposition()``
a Prometheus-style text page (counters/gauges as-is, histograms as
summaries with p50/p95/p99 quantile lines).

Callbacks let existing stat objects (e.g. ``PipelineMetrics``'s plain-int
counters) appear in snapshots without paying any registry cost when they
update: the registry calls them at snapshot time only.
"""

from __future__ import annotations

import json
import re
import threading
from typing import Callable

from .histogram import LatencyHistogram


class Counter:
    """Monotonic counter.  ``n`` is a plain int — increment it directly
    on hot paths (``c.n += k``); ``inc`` is the readable spelling."""

    __slots__ = ("n",)

    def __init__(self):
        self.n = 0

    def inc(self, k: int = 1) -> None:
        self.n += k

    @property
    def value(self) -> int:
        return self.n

    def __repr__(self):
        return f"Counter({self.n})"


class Gauge:
    """Last-written value, with additive updates and a high watermark.

    ``set`` remains the single-writer spelling; ``inc``/``dec`` are the
    MULTI-writer spelling — several channels bound to one gauge name
    (e.g. the R senders of a fan-out all publishing
    ``node.tx_queue_depth``) compose additively instead of clobbering
    each other with absolute reads-then-sets.  Same atomicity contract
    as :class:`Counter`: a GIL-level race costs one update, never a
    corrupt value.  ``hi`` tracks the max value seen since the last
    :meth:`take_watermark` — the queue-depth watermark an obs_push
    reports per interval.
    """

    __slots__ = ("v", "hi")

    def __init__(self):
        self.v = 0.0
        self.hi = 0.0

    def set(self, v: float) -> None:
        self.v = v
        if v > self.hi:
            self.hi = v

    def inc(self, k: float = 1.0) -> None:
        v = self.v + k
        self.v = v
        if v > self.hi:
            self.hi = v

    def dec(self, k: float = 1.0) -> None:
        self.v -= k

    def take_watermark(self) -> float:
        """Max value since the previous call; resets to the current value
        (so each reporting interval sees its own peak)."""
        h = self.hi if self.hi > self.v else self.v
        self.hi = self.v
        return h

    @property
    def value(self) -> float:
        return self.v

    def __repr__(self):
        return f"Gauge({self.v})"


def _prom_name(name: str) -> str:
    """Dotted metric name -> Prometheus-legal name (``[a-zA-Z_:]`` first
    char, ``[a-zA-Z0-9_:]`` after)."""
    n = re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    if not n or n[0].isdigit():
        n = "_" + n
    return n


def _prom_escape(text: str) -> str:
    """Escape a HELP line per the Prometheus text format: backslash and
    newline (HELP text is not quoted, so quotes pass through)."""
    return text.replace("\\", r"\\").replace("\n", r"\n")


def _prom_label_value(text: str) -> str:
    """Escape a label VALUE per the text format: backslash, double
    quote, newline."""
    return (text.replace("\\", r"\\").replace('"', r"\"")
            .replace("\n", r"\n"))


class MetricsRegistry:
    """Named instruments with get-or-create semantics.

    Creation is locked (threads race to register the same name and must
    get the same object); reads/increments touch the instrument directly.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, object] = {}
        self._callbacks: dict[str, Callable[[], object]] = {}

    # -- creation ----------------------------------------------------------

    def _get_or_create(self, name: str, cls):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls()
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} is {type(m).__name__}, "
                    f"not {cls.__name__}")
            return m

    def counter(self, name: str) -> Counter:
        return self._get_or_create(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get_or_create(name, Gauge)

    def histogram(self, name: str) -> LatencyHistogram:
        return self._get_or_create(name, LatencyHistogram)

    def register(self, name: str, instrument, weak: bool = False) -> None:
        """Attach an externally-owned instrument under ``name`` (e.g. a
        histogram living inside ``PipelineMetrics``).  Re-registering the
        same name replaces the entry — deployments are rebuilt in place.
        ``weak=True`` holds the instrument by weakref: once its owner is
        collected the entry is pruned at the next snapshot, so transient
        deployments don't grow the registry forever."""
        import weakref
        with self._lock:
            self._metrics[name] = weakref.ref(instrument) if weak \
                else instrument

    def register_callback(self, name: str,
                          fn: Callable[[], object]) -> None:
        """``fn()`` is evaluated at snapshot time; zero steady-state cost."""
        with self._lock:
            self._callbacks[name] = fn

    def unregister(self, prefix: str) -> None:
        """Drop every instrument/callback whose name starts with ``prefix``."""
        with self._lock:
            for d in (self._metrics, self._callbacks):
                for k in [k for k in d if k.startswith(prefix)]:
                    del d[k]

    def clear(self) -> None:
        with self._lock:
            self._metrics.clear()
            self._callbacks.clear()

    # -- export ------------------------------------------------------------

    def _live_metrics(self) -> dict:
        """Snapshot of the instrument dict with weakrefs resolved; dead
        weak entries are pruned in place (their owner was collected)."""
        import weakref
        with self._lock:
            out = {}
            dead = []
            for name, m in self._metrics.items():
                if isinstance(m, weakref.ref):
                    m = m()
                    if m is None:
                        dead.append(name)
                        continue
                out[name] = m
            for name in dead:
                del self._metrics[name]
            return out

    def snapshot(self) -> dict:
        """JSON-ready view: counters/gauges as numbers, histograms as
        {count, sum, mean, min, p50, p95, p99, max} summaries.

        Expiry contract: a callback returning ``None`` marks itself
        expired (its source was collected) and is pruned, as are dead
        weak-registered instruments — so transient deployments do not
        accumulate in the registry forever."""
        metrics = self._live_metrics()
        with self._lock:
            callbacks = dict(self._callbacks)
        out: dict = {}
        for name, m in sorted(metrics.items()):
            if isinstance(m, LatencyHistogram):
                out[name] = m.summary()
            elif isinstance(m, (Counter, Gauge)):
                out[name] = m.value
            else:  # foreign instrument: best effort
                out[name] = getattr(m, "value", repr(m))
        expired = []
        for name, fn in sorted(callbacks.items()):
            try:
                v = fn()
            except Exception as e:  # noqa: BLE001 — a dead callback must
                out[name] = f"<callback error: {e!r}>"  # not kill export
                continue
            if v is not None:
                out[name] = v
            else:
                expired.append(name)
        if expired:
            with self._lock:
                for name in expired:
                    self._callbacks.pop(name, None)
        return out

    def exposition(self) -> str:
        """Prometheus text format (histograms as summaries).

        Hardened per the text-format spec: every family gets a ``# HELP``
        line (carrying the original dotted name, escaped), metric names
        are sanitized to the legal charset (never digit-first), and
        label values are escaped — so a scraper / promtool never chokes
        on a creatively-named instrument."""
        metrics = self._live_metrics()
        with self._lock:
            callbacks = dict(self._callbacks)
        lines: list[str] = []

        def family(name: str, kind: str) -> str:
            pn = _prom_name(name)
            lines.append(f"# HELP {pn} defer_tpu metric "
                         f"{_prom_escape(name)}")
            lines.append(f"# TYPE {pn} {kind}")
            return pn

        for name, m in sorted(metrics.items()):
            if isinstance(m, LatencyHistogram):
                pn = family(name, "summary")
                for q in (0.5, 0.95, 0.99):
                    lines.append(
                        f'{pn}{{quantile="{_prom_label_value(str(q))}"}} '
                        f'{m.quantile(q):.9g}')
                lines.append(f"{pn}_sum {m.sum:.9g}")
                lines.append(f"{pn}_count {m.count}")
            elif isinstance(m, Counter):
                pn = family(name, "counter")
                lines.append(f"{pn} {m.value}")
            elif isinstance(m, Gauge):
                pn = family(name, "gauge")
                lines.append(f"{pn} {m.value:.9g}")
        for name, fn in sorted(callbacks.items()):
            try:
                v = fn()
            except Exception:  # noqa: BLE001 — skip dead callbacks
                continue
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                pn = family(name, "gauge")
                lines.append(f"{pn} {v:.9g}" if isinstance(v, float)
                             else f"{pn} {v}")
        return "\n".join(lines) + "\n"

    def dump_json(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.snapshot(), f, indent=2, default=str)
            f.write("\n")


#: the process-wide registry every subsystem instruments into
REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return REGISTRY
