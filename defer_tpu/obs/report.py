"""Node-side push telemetry: the obs reporter thread and the Prometheus
scrape endpoint.

A stage node answers ``{"cmd": "obs_subscribe", "interval_ms": 250}`` on
any control connection by starting one :class:`ObsReporter` bound to that
connection: a daemon thread that periodically builds an ``obs_push``
control frame from the node's live state (``StageNode.obs_snapshot``)
and writes it back on the same socket — no new ports, the push plane
rides the existing K_CTRL channel.  The reporter is self-cleaning: the
first failed send (subscriber closed the connection, node tearing down)
ends the thread.

:func:`start_prom_server` is the pull-side alternative: a stdlib
``http.server`` endpoint serving ``MetricsRegistry.exposition()`` for a
Prometheus scraper (``--prom-port`` on the ``node``/``chain`` CLIs).
"""

from __future__ import annotations

import threading

from .events import recorder
from .registry import REGISTRY
from .trace import tracer


class WatermarkSplit:
    """Per-subscriber fan-out of reset-on-read channel watermarks.

    A channel's ``take_watermark()`` is destructive — the peak since the
    LAST read, whoever read it.  With two concurrent subscribers (the
    serve front door's shedding loop and a human ``monitor``) each would
    see only the peaks since ANY subscriber's last push, splitting a
    burst across their reports.  This splitter is the node-side fix
    (CHANGES.md PR 5 known issue): every underlying take is folded into
    EVERY registered subscriber's running maximum, and a subscriber's
    own take drains only ITS accumulator — each subscriber sees the true
    peak since its own last read.

    Unregistered callers (direct ``obs_snapshot`` calls, tests) still
    get the raw fold — their reads never subtract from a subscriber's
    view.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._subs: dict[int, dict[str, int]] = {}

    def register(self, sid: int) -> None:
        with self._lock:
            self._subs.setdefault(sid, {})

    def unregister(self, sid: int) -> None:
        with self._lock:
            self._subs.pop(sid, None)

    def subscribers(self) -> int:
        with self._lock:
            return len(self._subs)

    def take(self, sid: int | None, key: str, chan) -> int:
        """Fold ``chan``'s watermark into every subscriber's view and
        return subscriber ``sid``'s accumulated peak (raw fold for
        ``sid=None`` / unknown)."""
        if chan is None:
            return 0
        with self._lock:
            hi = int(chan.take_watermark())
            for acc in self._subs.values():
                if hi > acc.get(key, 0):
                    acc[key] = hi
            acc = self._subs.get(sid) if sid is not None else None
            if acc is None:
                return hi
            return acc.pop(key, 0)


class ObsReporter(threading.Thread):
    """Per-subscription push thread (one per ``obs_subscribe``).

    ``source`` supplies the payload: an object with
    ``obs_snapshot(cursor, include_spans, span_limit) -> (dict, cursor)``
    (``StageNode`` implements it).  The span cursor starts at the
    subscription instant, so pushes carry only spans recorded since —
    and never drain the buffer ``trace_dump`` collects at stream end.
    """

    def __init__(self, source, conn, *, interval_s: float = 0.25,
                 spans: bool = True, span_limit: int = 256):
        super().__init__(daemon=True, name="obs-reporter")
        self._source = source
        self._conn = conn
        self.interval_s = max(0.02, float(interval_s))
        self._spans = spans
        self._span_limit = span_limit
        # NOT named _stop: threading.Thread's own machinery calls
        # self._stop() as a METHOD when a dead thread's is_alive() is
        # checked — shadowing it with an Event breaks that call
        self._halt = threading.Event()
        self._cursor = tracer().span_cursor()
        #: flight-recorder cursor: pushes carry only events emitted
        #: since the subscription instant (obs/events.py)
        self._ev_cursor = recorder().cursor()
        #: per-subscriber identity for the source's watermark splitter
        #: (each subscription sees peaks since ITS own last push)
        self.sid = id(self)

    def _snapshot(self):
        """One source snapshot, tolerant of the source's vintage: the
        current contract returns ``(payload, span_cursor,
        event_cursor)``; older sources (tests, external stubs) may
        return two values or reject the newer keywords."""
        try:
            out = self._source.obs_snapshot(
                cursor=self._cursor, include_spans=self._spans,
                span_limit=self._span_limit, subscriber=self.sid,
                event_cursor=self._ev_cursor)
        except TypeError:
            try:
                out = self._source.obs_snapshot(
                    cursor=self._cursor, include_spans=self._spans,
                    span_limit=self._span_limit, subscriber=self.sid)
            except TypeError:
                # source predates per-subscriber watermark splitting
                out = self._source.obs_snapshot(
                    cursor=self._cursor, include_spans=self._spans,
                    span_limit=self._span_limit)
        if len(out) == 3:
            payload, self._cursor, self._ev_cursor = out
        else:
            payload, self._cursor = out
        return payload

    def run(self) -> None:
        from ..transport.framed import send_ctrl
        register = getattr(self._source, "obs_register", None)
        if register is not None:
            register(self.sid)
        seq = 0
        try:
            while not self._halt.is_set():
                payload = self._snapshot()
                try:
                    payload["cmd"] = "obs_push"
                    payload["push_seq"] = seq
                    payload["interval_ms"] = round(
                        self.interval_s * 1e3, 3)
                    payload["t_us"] = tracer().now_us()
                    send_ctrl(self._conn, payload)
                except (OSError, ValueError):
                    return  # subscriber gone / socket closed: self-clean
                seq += 1
                self._halt.wait(self.interval_s)
        finally:
            unregister = getattr(self._source, "obs_unregister", None)
            if unregister is not None:
                unregister(self.sid)

    def stop(self) -> None:
        self._halt.set()


def start_prom_server(port: int, *, host: str = "127.0.0.1",
                      registry=None):
    """Serve ``registry.exposition()`` at ``http://host:port/metrics``
    (any path answers, as scrapers sometimes probe ``/``) on a daemon
    thread.  Returns the ``ThreadingHTTPServer``; its actual bound port
    is ``server.server_address[1]`` (pass ``port=0`` for an ephemeral
    one).  Stdlib only — no prometheus_client dependency."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    reg = registry if registry is not None else REGISTRY

    class _Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 — http.server API
            body = reg.exposition().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # noqa: ARG002 — silence stderr
            pass

    srv = ThreadingHTTPServer((host, port), _Handler)
    threading.Thread(target=srv.serve_forever, daemon=True,
                     name="prom-http").start()
    return srv
