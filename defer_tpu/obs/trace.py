"""Span tracer: trace_id/span_id spans exportable as Chrome trace JSON.

One :class:`Tracer` per process (module singleton via :func:`tracer`),
disabled by default.  The cost contract instrumentation sites rely on:

* disabled: ``tracer().enabled`` is one attribute read + branch;
  ``span()`` on a disabled tracer returns a shared no-op context manager.
* enabled: finishing a span is one dict construction + one list append
  under the GIL (O(1), no I/O, no locks on the hot path).

Spans carry ``trace_id``/``span_id``/``parent_id`` links.  Timestamps are
monotonic (``perf_counter``) anchored once to the wall clock, so spans
from different processes on one machine line up on a shared axis when
stitched — the cross-process MPMD chain ships its spans back to the
dispatcher via a ``trace_dump`` control frame (``runtime/node.py``) and
they merge here via :meth:`Tracer.ingest`.

Export is the Chrome trace-event format (``{"traceEvents": [...]}``):
open the file at https://ui.perfetto.dev or chrome://tracing.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
import uuid

from .registry import REGISTRY

#: incremented whenever a span is evicted from a full buffer — the
#: visible price of the cap (docs/OBSERVABILITY.md, overhead notes)
_DROPPED = REGISTRY.counter("trace.dropped_spans")

#: callbacks invoked with ``delta_us`` whenever the process tracer's
#: wall anchor shifts (clock alignment): other timeline-stamped buffers
#: — the flight recorder's event ring (obs/events.py) — register here
#: so their buffered entries stay coherent with the shifted spans
_ANCHOR_HOOKS: list = []


def register_anchor_hook(fn) -> None:
    """Register ``fn(delta_us)`` to run on every wall-anchor shift of
    the process tracer."""
    _ANCHOR_HOOKS.append(fn)


def _new_id() -> str:
    return uuid.uuid4().hex[:16]


#: public alias: pre-allocate a span id (see ``Tracer.record(span_id=...)``)
new_span_id = _new_id


class _Span:
    """Context manager for one span; created only when tracing is on."""

    __slots__ = ("_tracer", "name", "trace_id", "parent_id", "span_id",
                 "args", "_t0")

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 parent_id: str | None, args: dict | None):
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.parent_id = parent_id
        self.span_id = _new_id()
        self.args = args
        self._t0 = 0.0

    def __enter__(self):
        stack = self._tracer._stack()
        stack.append(self)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = time.perf_counter()
        stack = self._tracer._stack()
        if stack and stack[-1] is self:
            stack.pop()
        args = self.args
        if exc_type is not None:
            args = dict(args or ())
            args["error"] = exc_type.__name__
        self._tracer._finish(self.name, self.trace_id, self.span_id,
                             self.parent_id, self._t0, t1 - self._t0, args)
        return False


class _NoopSpan:
    """Shared do-nothing span for the disabled path (no allocation)."""

    __slots__ = ()
    span_id = None
    trace_id = None

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


_NOOP = _NoopSpan()


class Tracer:
    #: default span-buffer cap (spans, not bytes).  A long traced stream
    #: must not grow memory without bound: past the cap the OLDEST span
    #: is evicted per append and ``trace.dropped_spans`` counts the loss.
    DEFAULT_MAX_SPANS = int(os.environ.get("DEFER_TRACE_MAX_SPANS",
                                           "200000") or 200000)

    def __init__(self, process: str | None = None, enabled: bool = False,
                 max_spans: int | None = None):
        #: the one predicate hot paths check
        self.enabled = enabled
        self.process = process or f"pid{os.getpid()}"
        self._spans: collections.deque[dict] = collections.deque()
        self.max_spans = (self.DEFAULT_MAX_SPANS if max_spans is None
                          else int(max_spans))
        #: spans evicted because the buffer was full (lifetime)
        self.dropped = 0
        #: spans ever removed from the FRONT of the buffer (drained,
        #: cleared, or evicted) — the anchor of the ``spans_since``
        #: cursor contract, so live subscribers can fetch incremental
        #: batches without draining what ``trace_dump`` will collect
        self._base = 0
        self._tls = threading.local()
        self._trace_id: str | None = None
        #: adopted remote parent (cross-process propagation target)
        self._remote_parent: str | None = None
        # wall-clock anchor: ts_us = wall0 + (mono - mono0), so per-process
        # monotonic clocks land on one shared (approximate) absolute axis
        self._wall0_us = time.time_ns() // 1_000
        self._mono0 = time.perf_counter()

    # -- trace identity ----------------------------------------------------

    @property
    def trace_id(self) -> str:
        """Current trace id, starting a trace on first use."""
        if self._trace_id is None:
            self._trace_id = _new_id()
        return self._trace_id

    def start_trace(self, trace_id: str | None = None) -> str:
        """Begin a new trace (fresh id unless given one to join)."""
        self._trace_id = trace_id or _new_id()
        self._remote_parent = None
        return self._trace_id

    def adopt(self, ctx: dict | None) -> None:
        """Join a remote trace: ``ctx`` is an :meth:`inject` dict carried
        over the wire (e.g. in a K_CTRL frame).  Subsequent root spans in
        this process parent under the remote span."""
        if not ctx or "trace_id" not in ctx:
            return
        self._trace_id = ctx["trace_id"]
        self._remote_parent = ctx.get("span_id")
        self.enabled = True

    def inject(self) -> dict:
        """Wire-format trace context: the current span (or remote parent)
        of this thread, under the current trace id."""
        stack = self._stack()
        parent = stack[-1].span_id if stack else self._remote_parent
        ctx = {"trace_id": self.trace_id}
        if parent:
            ctx["span_id"] = parent
        return ctx

    # -- recording ---------------------------------------------------------

    def _stack(self) -> list:
        s = getattr(self._tls, "stack", None)
        if s is None:
            s = self._tls.stack = []
        return s

    def span(self, name: str, args: dict | None = None):
        """Context manager for a timed span; no-op when disabled."""
        if not self.enabled:
            return _NOOP
        stack = self._stack()
        parent = stack[-1].span_id if stack else self._remote_parent
        return _Span(self, name, self.trace_id, parent, args)

    def record(self, name: str, t0: float, dur_s: float,
               args: dict | None = None,
               parent_id: str | None = None,
               span_id: str | None = None) -> None:
        """Record an already-timed interval as a span (O(1) append).

        ``t0`` is a ``perf_counter`` timestamp.  The caller checks
        ``enabled`` first — that predicate is the whole disabled cost.
        ``span_id`` lets a caller pre-allocate the id (``new_span_id``) so
        children — possibly in other processes — can parent under a span
        recorded only when the enclosing work finishes."""
        if parent_id is None:
            stack = self._stack()
            parent_id = stack[-1].span_id if stack else self._remote_parent
        self._finish(name, self.trace_id, span_id or _new_id(), parent_id,
                     t0, dur_s, args)

    def _finish(self, name, trace_id, span_id, parent_id, t0, dur_s, args):
        self._spans.append({
            "name": name,
            "trace": trace_id,
            "span": span_id,
            "parent": parent_id,
            "ts_us": self._wall0_us + int((t0 - self._mono0) * 1e6),
            "dur_us": max(int(dur_s * 1e6), 1),
            "proc": self.process,
            "tid": threading.get_ident() & 0xFFFF,
            "args": args or {},
        })
        if len(self._spans) > self.max_spans:
            self._evict(len(self._spans) - self.max_spans)

    def _evict(self, n: int) -> None:
        """Drop the ``n`` oldest spans (buffer cap): recent spans are the
        ones a live monitor and an end-of-stream dump still want.  A
        concurrent ``drain`` may empty the buffer between the length
        check and the pop — losing the eviction race just means the
        drain already made room."""
        popped = 0
        for _ in range(n):
            try:
                self._spans.popleft()
            except IndexError:
                break
            popped += 1
        self.dropped += popped
        self._base += popped
        _DROPPED.n += popped

    # -- clock alignment ----------------------------------------------------

    def now_us(self) -> int:
        """This process's current position on the span timeline (the same
        anchor ``_finish`` stamps ``ts_us`` with) — what a clock-offset
        probe compares across processes."""
        return self._wall0_us + int(
            (time.perf_counter() - self._mono0) * 1e6)

    def shift_wall_anchor(self, delta_us: int) -> None:
        """Shift the wall anchor by ``delta_us`` — clock alignment after a
        ping-pong offset estimate (``obs.cluster.estimate_clock_offset``).
        Already-buffered spans shift too, so the whole dump stays on one
        coherent axis no matter when the correction landed.

        Iterates a snapshot (``list(deque)`` is atomic under the GIL, a
        Python-level loop over the live deque is not): hot-path threads
        may append WHILE the anchor shifts, and a span stamped with the
        old anchor in that window stays unshifted — a one-span, one-time
        telemetry error, vs. a RuntimeError that would kill the
        connection worker applying a ``clock_adjust``."""
        delta_us = int(delta_us)
        self._wall0_us += delta_us
        for s in list(self._spans):
            s["ts_us"] += delta_us
        if self is _TRACER:
            # coupled timeline buffers (the flight recorder) shift with
            # the PROCESS tracer only — test-local Tracer instances must
            # not drag the process event ring around
            for fn in _ANCHOR_HOOKS:
                fn(delta_us)

    # -- cross-process stitching -------------------------------------------

    def drain(self) -> list[dict]:
        """Pop all recorded spans (the ship-over-the-wire form).

        Element-wise popleft, not snapshot+clear: a span appended by a
        concurrent hot-path thread mid-drain is either drained or left
        for the next drain — never silently lost between the copy and
        the clear."""
        spans: list[dict] = []
        while True:
            try:
                spans.append(self._spans.popleft())
            except IndexError:
                break
        self._base += len(spans)
        return spans

    def ingest(self, spans: list[dict]) -> None:
        """Merge spans drained from another process's tracer."""
        self._spans.extend(spans)
        if len(self._spans) > self.max_spans:
            self._evict(len(self._spans) - self.max_spans)

    def span_cursor(self) -> int:
        """Monotone count of spans ever finished in this tracer — pass it
        back to :meth:`spans_since` for an incremental batch."""
        return self._base + len(self._spans)

    def spans_since(self, cursor: int, limit: int | None = None
                    ) -> tuple[int, list[dict]]:
        """(new_cursor, spans finished after ``cursor``) WITHOUT draining:
        a live subscriber (obs_push span batches) reads incrementally
        while ``trace_dump`` still collects everything at stream end.
        ``limit`` keeps only the newest N of the batch (push size bound);
        spans evicted or drained before the read are simply gone.

        Reads a snapshot first — ``list(deque)`` is GIL-atomic, whereas
        islice over the live deque would raise if a hot-path thread
        appended mid-iteration (the reporter thread calls this while
        the stream is recording)."""
        base = self._base
        snapshot = list(self._spans)
        start = max(0, cursor - base)
        out = snapshot[start:]
        if limit is not None and len(out) > limit:
            out = out[-limit:]
        return base + len(snapshot), out

    @property
    def spans(self) -> list[dict]:
        return list(self._spans)

    def clear(self) -> None:
        self.drain()

    # -- export ------------------------------------------------------------

    def chrome_events(self) -> list[dict]:
        """Spans as Chrome trace-event dicts (complete events, ph="X")."""
        pids: dict[str, int] = {}
        events: list[dict] = []
        for s in list(self._spans):  # snapshot: appends may race export
            proc = s.get("proc", "?")
            pid = pids.get(proc)
            if pid is None:
                pid = pids[proc] = len(pids) + 1
                events.append({"name": "process_name", "ph": "M",
                               "pid": pid, "tid": 0,
                               "args": {"name": proc}})
            args = dict(s.get("args") or ())
            args["trace_id"] = s.get("trace")
            args["span_id"] = s.get("span")
            if s.get("parent"):
                args["parent_span_id"] = s["parent"]
            events.append({
                "name": s["name"], "ph": "X", "cat": "defer",
                "ts": s["ts_us"], "dur": s["dur_us"],
                "pid": pid, "tid": s.get("tid", 0), "args": args,
            })
        return events

    def export_chrome(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump({"traceEvents": self.chrome_events(),
                       "displayTimeUnit": "ms"}, f)
            f.write("\n")


#: process singleton
_TRACER = Tracer()


def tracer() -> Tracer:
    return _TRACER


def enable_tracing(process: str | None = None) -> Tracer:
    """Turn the process tracer on (idempotent); returns it."""
    if process:
        _TRACER.process = process
    _TRACER.enabled = True
    return _TRACER


def trace_context() -> dict | None:
    """Wire context of the current trace, or None when tracing is off —
    the one-liner callers put into a K_CTRL frame."""
    return _TRACER.inject() if _TRACER.enabled else None


def export_chrome_trace(path: str) -> None:
    """Write the process tracer's spans as Chrome trace JSON."""
    _TRACER.export_chrome(path)
