"""Pallas TPU kernels for the hot ops.

The reference delegates all compute to Keras ``model.predict`` (reference
src/node.py:106); here the few ops that dominate wall-clock get hand-tiled
Pallas kernels (MXU-aligned blocks, VMEM-resident working set), with the
plain-XLA implementations as the fallback everywhere else.
"""

from .flash_attention import flash_attention
from .quant import (BLOCK as QUANT_BLOCK, dequantize_int8_blocks,
                    quantize_int8_blocks)

__all__ = ["flash_attention", "QUANT_BLOCK", "quantize_int8_blocks",
           "dequantize_int8_blocks"]
