"""Fused flash attention as a Pallas TPU kernel.

The reference framework has no attention at all (CNN workloads only —
SURVEY.md §2.3); attention is first-class here because the BERT-Base/12
baseline config and the long-context (ring attention) path both spend their
FLOPs in it.  This kernel computes exact softmax attention in O(T) memory by
streaming K/V blocks through VMEM with an online-softmax accumulator —
neither the score matrix [Tq, Tk] nor the full K/V sequence is ever resident
on-chip.

Tiling: grid = (batch*heads, Tq/block_q, Tk/block_k) with the K axis
innermost; Pallas DMAs one [block_k, d] K/V tile per step while the
(running max, running denominator, rescaled accumulator) state persists in
VMEM scratch across the sequential K iterations.  Both matmuls per block
(QK^T and PV) hit the MXU at [block_q, d] x [d, block_k] and
[block_q, block_k] x [block_k, d].

Causal masking uses bottom-right alignment: query row i attends to key
positions <= i + (Tk - Tq), so decode-style calls (Tq=1 against a long K/V
prefix) attend to the whole prefix.

On non-TPU backends (CPU tests) the same kernel runs in interpreter mode, so
there is exactly one implementation of the math.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = float("-inf")
#: lane width of the m/l scratch rows (per-row scalars broadcast across it)
_LANES = 128


def _attn_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *,
                 scale, block_q, block_k, num_kb, t_q, t_k, causal):
    qi = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # bottom-right causal alignment: q row r is global position
    # r + qi*block_q + (t_k - t_q) in key coordinates
    causal_off = t_k - t_q
    if causal:
        # this K block is fully in the future of every query row -> skip
        live = kb * block_k <= qi * block_q + block_q - 1 + causal_off
    else:
        live = True

    @pl.when(live)
    def _accumulate():
        q = q_ref[0].astype(jnp.float32)  # [block_q, d]
        k = k_ref[0].astype(jnp.float32)  # [block_k, d]
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]

        k_pos = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = k_pos < t_k  # drop key padding
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0) + causal_off
            mask = jnp.logical_and(mask, q_pos >= k_pos)
        s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_ref[:, :1]  # [bq, 1]
        l_prev = l_ref[:, :1]
        m_blk = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_blk)
        # rows with no unmasked key yet carry m = -inf; keep them inert
        safe_m = jnp.where(m_new == _NEG_INF, 0.0, m_new)
        alpha = jnp.where(m_prev == _NEG_INF, 0.0, jnp.exp(m_prev - safe_m))
        p = jnp.where(mask, jnp.exp(s - safe_m), 0.0)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(kb == num_kb - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, :1], 1e-20)
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)


def _pad_to(x, axis, mult):
    size = x.shape[axis]
    rem = -size % mult
    if rem == 0:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, rem)
    return jnp.pad(x, pad)


@functools.partial(jax.jit, static_argnames=(
    "causal", "block_q", "block_k", "interpret"))
def flash_attention(q, k, v, *, causal: bool = False, block_q: int = 128,
                    block_k: int = 128, interpret: bool | None = None):
    """Exact attention ``softmax(q kᵀ/√d) v`` without materializing scores.

    q: [B, H, Tq, D]; k, v: [B, H, Tk, D].  Any sizes — inputs are padded to
    MXU-aligned tiles internally and the padding is masked out of the
    softmax.  ``causal=True`` with Tq != Tk uses bottom-right alignment
    (decode semantics).  ``interpret=None`` auto-selects interpreter mode
    off-TPU so tests exercise the identical kernel on CPU.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, h, t_q, d = q.shape
    t_k = k.shape[2]
    scale = 1.0 / math.sqrt(d)
    orig_dtype = q.dtype

    block_q = min(block_q, max(8, 1 << (t_q - 1).bit_length()))
    block_k = min(block_k, max(8, 1 << (t_k - 1).bit_length()))

    qp = _pad_to(q.reshape(b * h, t_q, d), 1, block_q)
    kp = _pad_to(k.reshape(b * h, t_k, d), 1, block_k)
    vp = _pad_to(v.reshape(b * h, t_k, d), 1, block_k)
    # pad head dim to the 128-lane boundary (zeros are exact: they add
    # nothing to q·k scores and the extra output columns are sliced off)
    qp, kp, vp = (_pad_to(x, 2, _LANES) for x in (qp, kp, vp))
    dp = qp.shape[-1]
    tqp, tkp = qp.shape[1], kp.shape[1]
    num_qb, num_kb = tqp // block_q, tkp // block_k

    kernel = functools.partial(
        _attn_kernel, scale=scale, block_q=block_q, block_k=block_k,
        num_kb=num_kb, t_q=t_q, t_k=t_k, causal=causal)

    out = pl.pallas_call(
        kernel,
        grid=(b * h, num_qb, num_kb),
        in_specs=[
            pl.BlockSpec((1, block_q, dp), lambda bh, qi, kb: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, dp), lambda bh, qi, kb: (bh, kb, 0)),
            pl.BlockSpec((1, block_k, dp), lambda bh, qi, kb: (bh, kb, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, dp),
                               lambda bh, qi, kb: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, tqp, dp), orig_dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # running max
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # running denom
            pltpu.VMEM((block_q, dp), jnp.float32),      # value accumulator
        ],
        interpret=interpret,
    )(qp, kp, vp)

    return out[:, :t_q, :d].reshape(b, h, t_q, d)
