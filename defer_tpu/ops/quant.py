"""Device-side block-scale int8 quantization for inter-stage transfers.

The TPU-idiomatic analogue of the reference's lossy ZFP activation
compression (reference src/node.py:107, src/dispatcher.py:92): instead of
CPU-side compression of the wire payload, activations are quantized to int8
with one float32 scale per 256-value block *in HBM, inside the compiled
program*, immediately before the stage-to-stage ``ppermute`` — ICI moves
~1.016 bytes/value instead of 2 (bf16) or 4 (f32) — and dequantized right
after.  Pure jnp; XLA fuses both sides into the neighboring stage programs.

Relative error is <= 1/254 of each block's max |value| (symmetric int8),
comparable to the default ZFP tolerance the reference ships.
"""

from __future__ import annotations

import jax.numpy as jnp

#: values per shared scale
BLOCK = 256


def quantize_int8_blocks(x: jnp.ndarray, use_pallas: bool | None = None):
    """[..., L] float -> ([..., L] int8, [..., L/BLOCK] f32 scales).

    L must be a multiple of BLOCK (the pipeline pads its transfer buffer
    up-front).  Non-finite inputs are flushed to 0 like the host codec.
    On TPU the fused Pallas kernel (``ops/quant_pallas.py``) runs instead
    of this jnp reference; pass ``use_pallas`` to force either path.
    """
    import jax
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    if use_pallas:
        from .quant_pallas import quantize_int8_blocks_pallas
        return quantize_int8_blocks_pallas(x)
    *lead, n = x.shape
    if n % BLOCK:
        raise ValueError(f"last dim {n} not a multiple of {BLOCK}")
    xb = x.reshape(*lead, n // BLOCK, BLOCK).astype(jnp.float32)
    xb = jnp.where(jnp.isfinite(xb), xb, 0.0)
    amax = jnp.max(jnp.abs(xb), axis=-1)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(xb / scale[..., None]), -127, 127).astype(jnp.int8)
    return q.reshape(*lead, n), scale


def quantized_ring_hop(y: jnp.ndarray, axis: str, perm, out_dtype):
    """The int8 stage->successor hop: block-quantize in HBM, ppermute the
    int8 payload + scales over ICI, dequantize on arrival.

    The single definition shared by the inference engine and the trainer's
    straight-through forward — training's forward must stay byte-identical
    to the wire it deploys."""
    from jax import lax
    q, s = quantize_int8_blocks(y)
    q = lax.ppermute(q, axis, perm)
    s = lax.ppermute(s, axis, perm)
    return dequantize_int8_blocks(q, s, out_dtype)


def dequantize_int8_blocks(q: jnp.ndarray, scale: jnp.ndarray,
                           dtype=jnp.float32):
    """Inverse of :func:`quantize_int8_blocks`."""
    *lead, n = q.shape
    xb = q.reshape(*lead, n // BLOCK, BLOCK).astype(jnp.float32)
    return (xb * scale[..., None]).reshape(*lead, n).astype(dtype)
