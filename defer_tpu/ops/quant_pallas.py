"""Pallas TPU kernel for block-scale int8 wire quantization.

One VMEM pass fuses the whole quantize step the jnp reference
(``ops/quant.py``) expresses as amax -> scale -> divide -> round -> clip:
each grid step DMAs one row-tile of the transfer buffer into VMEM, computes
per-256-value-block scales, and stores the int8 payload plus f32 scales.
This is the hot half of the ``wire="int8"`` path (it runs every pipeline
step on every device, immediately before the stage->stage ``ppermute`` —
runtime/spmd.py); dequantize stays plain jnp because XLA fuses a single
multiply into the consuming stage for free.

Off-TPU the identical kernel runs in interpreter mode (same math, one
implementation) — the pattern established by ``ops/flash_attention.py``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .quant import BLOCK

#: row-tile width per grid step (multiple of BLOCK; 8 blocks = 2 KiB int8)
_TILE = 8 * BLOCK


def _quant_kernel(x_ref, q_ref, s_ref):
    x = x_ref[...].astype(jnp.float32)          # [1, tile]
    xb = x.reshape(-1, BLOCK)                   # [tile/BLOCK, BLOCK]
    xb = jnp.where(jnp.isfinite(xb), xb, 0.0)
    amax = jnp.max(jnp.abs(xb), axis=-1, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(xb / scale), -127, 127).astype(jnp.int8)
    q_ref[...] = q.reshape(x_ref.shape)
    s_ref[...] = scale.reshape(s_ref.shape)


@functools.partial(jax.jit, static_argnames=("interpret",))
def quantize_int8_blocks_pallas(x: jnp.ndarray,
                                interpret: bool | None = None):
    """Drop-in Pallas version of ``quant.quantize_int8_blocks``.

    [..., L] float -> ([..., L] int8, [..., L/BLOCK] f32), L % BLOCK == 0.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    *lead, n = x.shape
    if n % BLOCK:
        raise ValueError(f"last dim {n} not a multiple of {BLOCK}")
    rows = 1
    for d in lead:
        rows *= d
    xf = x.reshape(rows, n)

    tile = _TILE if n % _TILE == 0 else BLOCK
    grid = (rows, n // tile)
    q, s = pl.pallas_call(
        _quant_kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((1, tile), lambda r, c: (r, c))],
        out_specs=[
            pl.BlockSpec((1, tile), lambda r, c: (r, c)),
            pl.BlockSpec((1, tile // BLOCK), lambda r, c: (r, c)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, n), jnp.int8),
            jax.ShapeDtypeStruct((rows, n // BLOCK), jnp.float32),
        ],
        interpret=interpret,
    )(xf)
    return q.reshape(*lead, n), s.reshape(*lead, n // BLOCK)
