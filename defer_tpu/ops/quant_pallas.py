"""Pallas TPU kernel for block-scale int8 wire quantization.

One VMEM pass fuses the whole quantize step the jnp reference
(``ops/quant.py``) expresses as amax -> scale -> divide -> round -> clip:
each grid step DMAs one row-tile of the transfer buffer into VMEM, computes
per-256-value-block scales, and stores the int8 payload plus f32 scales.
This is the hot half of the ``wire="int8"`` path (it runs every pipeline
step on every device, immediately before the stage->stage ``ppermute`` —
runtime/spmd.py); dequantize stays plain jnp because XLA fuses a single
multiply into the consuming stage for free.

Off-TPU the identical kernel runs in interpreter mode (same math, one
implementation) — the pattern established by ``ops/flash_attention.py``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .quant import BLOCK

#: quant blocks handled per grid step.  The kernel views the input as
#: [n_blocks, BLOCK] — one 256-value quant block per row — so the Pallas
#: block shape is (_ROWS, BLOCK): both dims satisfy the TPU tiling rule
#: (rows divisible by 8, lanes divisible by 128), and the scale output's
#: (_ROWS, 1) block is legal because 1 IS its array's full last dim.
#: 128 rows x 256 lanes = 128 KiB f32 in VMEM per step.
_ROWS = 128


def _quant_kernel(x_ref, q_ref, s_ref):
    x = x_ref[...].astype(jnp.float32)          # [_ROWS, BLOCK]
    x = jnp.where(jnp.isfinite(x), x, 0.0)
    amax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q_ref[...] = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    s_ref[...] = scale


@functools.partial(jax.jit, static_argnames=("interpret",))
def quantize_int8_blocks_pallas(x: jnp.ndarray,
                                interpret: bool | None = None):
    """Drop-in Pallas version of ``quant.quantize_int8_blocks``.

    [..., L] float -> ([..., L] int8, [..., L/BLOCK] f32), L % BLOCK == 0.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    *lead, n = x.shape
    if n % BLOCK:
        raise ValueError(f"last dim {n} not a multiple of {BLOCK}")
    rows = 1
    for d in lead:
        rows *= d
    nblocks = rows * (n // BLOCK)
    xf = x.reshape(nblocks, BLOCK)

    # ragged edge is safe: each row is one independent quant block, so the
    # garbage Pallas pads the final partial tile with never reaches a real
    # row's scale or payload
    grid = (pl.cdiv(nblocks, _ROWS),)
    q, s = pl.pallas_call(
        _quant_kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((_ROWS, BLOCK), lambda r: (r, 0))],
        out_specs=[
            pl.BlockSpec((_ROWS, BLOCK), lambda r: (r, 0)),
            pl.BlockSpec((_ROWS, 1), lambda r: (r, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((nblocks, BLOCK), jnp.int8),
            jax.ShapeDtypeStruct((nblocks, 1), jnp.float32),
        ],
        interpret=interpret,
    )(xf)
    return q.reshape(*lead, n), s.reshape(*lead, n // BLOCK)
