from .mesh import DATA_AXIS, STAGE_AXIS, pipeline_mesh, stage_axis_size
from .ring_attention import (SEQ_AXIS, full_attention, ring_attention,
                             sequence_parallel_attention)
from .tensor import (MODEL_AXIS, shard_tp_params, tensor_parallel_fn,
                     tensor_parallel_mesh)
