from .mesh import DATA_AXIS, STAGE_AXIS, pipeline_mesh, stage_axis_size
from .ring_attention import (SEQ_AXIS, full_attention, ring_attention,
                             sequence_parallel_attention)
