from .mesh import DATA_AXIS, STAGE_AXIS, pipeline_mesh, stage_axis_size
