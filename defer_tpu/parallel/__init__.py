from .mesh import DATA_AXIS, STAGE_AXIS, pipeline_mesh, stage_axis_size
from .ring_attention import (SEQ_AXIS, full_attention, ring_attention,
                             sequence_parallel_attention)
from .distributed import (initialize, multihost_pipeline_mesh,
                          process_local_batch)
from .expert import (EXPERT_AXIS, expert_parallel_fn, expert_parallel_mesh,
                     shard_moe_params)
from .tensor import (MODEL_AXIS, shard_tp_params, tensor_parallel_fn,
                     tensor_parallel_mesh)
from .ulysses import (sequence_parallel_attention_ulysses,
                      ulysses_attention)
