"""Multi-host distributed runtime: DCN-aware initialization and meshes.

The reference scales across machines with a hand-rolled TCP fabric — each
node is a standalone process bound to fixed ports, chained by the
dispatcher sending every node its successor's IP (reference
src/dispatcher.py:51-55, src/node.py:17,29,100).  The TPU-native answer is
JAX's multi-controller runtime: every host runs the same program,
``jax.distributed.initialize`` wires the hosts into one global device set,
and a global ``Mesh`` spanning all hosts routes stage-axis neighbors over
ICI within a slice and DCN between slices — no first-party sockets, ports,
or IP exchange anywhere.

On a single host everything here degrades gracefully: ``initialize`` is a
no-op and the meshes fall back to local devices.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh

from .mesh import pipeline_mesh

_initialized = False


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None) -> None:
    """Join the multi-host runtime (idempotent; no-op when single-host).

    The moral replacement for the reference's model/weights/data port
    handshake (src/node.py:20-75): after this call every host sees the
    global ``jax.devices()`` list and compiled programs place collectives
    over ICI/DCN automatically.  With no arguments, environment-provided
    cluster configuration (TPU metadata, SLURM, etc.) is used.
    """
    global _initialized
    if _initialized:
        return
    # NOTE: nothing here may touch jax.devices()/process_count() first —
    # that would initialize the XLA backend and make distributed init
    # impossible ("must be called before any JAX computations").
    if coordinator_address is None and num_processes is None:
        # env-autoconfigured (TPU pod metadata, SLURM, ...) or single-host;
        # autoconfig raises on a plain single host -> graceful no-op.
        # Deliberately NOT latched: a later call with explicit coordinator
        # args must still be able to form the cluster.
        try:
            jax.distributed.initialize()
        except (ValueError, RuntimeError):
            return
    else:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id)
    _initialized = True


def multihost_pipeline_mesh(num_stages: int, data_parallel: int = 1,
                            tensor_parallel: int = 1) -> Mesh:
    """Global pipeline mesh over every device of every host.

    Layout policy (the DCN/ICI split from the scaling-book recipe): the
    stage axis is ordered so consecutive stages stay on the same host
    (slice) wherever possible — stage hops ride ICI and only the
    once-per-host boundary hop crosses DCN, mirroring how the reference's
    chain crosses machines once per node boundary.  The data axis, if any,
    is outermost (one pipeline replica per host group).
    """
    # jax.devices() is the global, process-spanning, host-major list, so
    # the shared layout policy applies unchanged across hosts
    return pipeline_mesh(num_stages, data_parallel, tensor_parallel,
                         devices=jax.devices())


def process_local_batch(global_batch: int) -> int:
    """Per-host share of a global batch (hosts feed disjoint input shards,
    the multi-controller analogue of the dispatcher's single input stream,
    reference src/dispatcher.py:85-93)."""
    n = jax.process_count()
    if global_batch % n:
        raise ValueError(f"global batch {global_batch} not divisible by "
                         f"{n} hosts")
    return global_batch // n
