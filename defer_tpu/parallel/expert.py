"""Expert parallelism: MoE experts sharded over an ``"expert"`` mesh axis
with capacity-based ``lax.all_to_all`` token dispatch.

Absent from the reference (CNN pipelines only — SURVEY.md §2.3) but part of
this framework's first-class parallelism inventory.  The design is the
standard switch-routing EP pattern: tokens are data-sharded over the expert
axis, each device owns ``E / ep`` experts, and two ``all_to_all`` exchanges
over ICI move (token → owning expert) and (result → originating device).

Numerics match the dense single-device :meth:`MoE.apply` exactly whenever no
expert's per-device token count exceeds capacity; overflow tokens are
dropped (their FFN delta is zero, residual passes through) — switch-style
capacity semantics.
"""

from __future__ import annotations

import math
from typing import Any

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..graph.ops import MoE
from ..utils.compat import shard_map

EXPERT_AXIS = "expert"


def expert_parallel_mesh(ep: int, devices=None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) < ep:
        raise ValueError(f"need {ep} devices, have {len(devices)}")
    return Mesh(np.array(devices[:ep]), (EXPERT_AXIS,))


def shard_moe_params(op: MoE, params: dict[str, Any], ep: int,
                     mesh: Mesh | None = None, axis: str = EXPERT_AXIS):
    """Stack per-rank expert shards on a leading [ep, ...] axis.

    The gate is replicated (every device routes identically); fc1/fc2 are
    sliced so rank r owns experts [r*E/ep, (r+1)*E/ep).
    """
    e = op.num_experts
    if e % ep:
        raise ValueError(f"num_experts={e} not divisible by ep={ep}")
    el = e // ep

    def rank_shard(r):
        sl = slice(r * el, (r + 1) * el)
        return {
            "gate": params["gate"],
            "fc1": {"w": params["fc1"]["w"][sl], "b": params["fc1"]["b"][sl]},
            "fc2": {"w": params["fc2"]["w"][sl], "b": params["fc2"]["b"][sl]},
        }

    shards = [rank_shard(r) for r in range(ep)]
    out = jax.tree.map(
        lambda *xs: np.stack([np.asarray(x) for x in xs]), *shards)
    if mesh is not None:
        out = jax.device_put(out, NamedSharding(mesh, P(axis)))
    return out


def expert_parallel_apply(op: MoE, params_local, x, *, axis_name: str,
                          ep: int, capacity: int):
    """One EP MoE layer on this device's token shard ``x`` [b_local, t, d].

    ``params_local`` holds this rank's expert slice (leading axis already
    indexed away).  Two ``all_to_all``s: dispatch and return.
    """
    b, t, d = x.shape
    n = b * t
    el = op.num_experts // ep
    xf = x.reshape(n, d)

    eid, pe = op.route(params_local, x)
    eidf, pef = eid.reshape(n), pe.reshape(n).astype(xf.dtype)
    dest = eidf // el                                    # owning rank
    # slot = this token's arrival index within its dest's capacity buffer
    dmask = jax.nn.one_hot(dest, ep, dtype=jnp.int32)
    pos = (jnp.cumsum(dmask, axis=0) * dmask).sum(-1) - 1
    keep = pos < capacity
    slot = jnp.where(keep, pos, capacity)                # overflow -> C (cut)

    # payload = token features + its local expert index; the gate prob stays
    # local (applied to the returned result), so it never rides the wire.
    # The index rides in the activation dtype, so it must be exactly
    # representable there: floats are integer-exact only up to
    # 2**(mantissa+1) (bf16: 256, f16: 2048), beyond which routing would
    # silently send tokens to the wrong local expert.
    exact_max = 2 ** (jnp.finfo(xf.dtype).nmant + 1)
    if el > exact_max:
        raise ValueError(
            f"{el} local experts per device cannot ride an {xf.dtype} "
            f"all_to_all payload exactly (max {exact_max}); use wider "
            f"activations or more expert-parallel ranks")
    lid = (eidf % el).astype(xf.dtype)
    payload = jnp.concatenate([xf, lid[:, None]], axis=-1)  # [n, d+1]
    buf = jnp.zeros((ep, capacity + 1, d + 1), xf.dtype)
    buf = buf.at[dest, slot].set(payload)
    send = buf[:, :capacity]

    recv = lax.all_to_all(send, axis_name, split_axis=0, concat_axis=0)
    xr = recv[..., :d]                                   # [ep, C, d]
    lidr = recv[..., d].astype(jnp.int32)

    # masked dense sweep over my local experts (el is small by design; the
    # dispatch already cut tokens/device by ~ep)
    y = jnp.zeros_like(xr)
    for e in range(el):
        ye = op.expert_fn(params_local, xr, jnp.asarray(e))
        y = jnp.where((lidr == e)[..., None], ye, y)

    back = lax.all_to_all(y, axis_name, split_axis=0, concat_axis=0)
    y_tok = back[dest, jnp.clip(slot, 0, capacity - 1)]  # [n, d]
    y_tok = y_tok * keep[:, None].astype(xf.dtype) * pef[:, None]
    return x + y_tok.reshape(b, t, d)


def expert_parallel_fn(op: MoE, mesh: Mesh, axis: str = EXPERT_AXIS,
                       capacity_factor: float = 2.0,
                       tokens_per_device: int | None = None):
    """Jitted EP forward: ``fn(stacked_params, x) -> y``.

    ``x`` [B, t, d] is sharded on its batch dim over the expert axis;
    ``stacked_params`` comes from :func:`shard_moe_params`.  Capacity per
    device is ``ceil(capacity_factor * tokens_per_device / ep)`` (computed
    from the first call's shapes unless given explicitly).
    """
    ep = mesh.shape[axis]

    def local(pstk, x):
        p = jax.tree.map(lambda a: a[0], pstk)
        ntok = tokens_per_device or x.shape[0] * x.shape[1]
        cap = max(1, math.ceil(capacity_factor * ntok / ep))
        return expert_parallel_apply(op, p, x, axis_name=axis, ep=ep,
                                     capacity=cap)

    fn = shard_map(local, mesh=mesh, in_specs=(P(axis), P(axis)),
                       out_specs=P(axis), check_vma=False)
    return jax.jit(fn)
