"""Device-mesh construction for the pipeline.

The reference's topology is a runtime-configured linear chain of TCP hosts —
the dispatcher tells each node its successor's IP (reference
src/dispatcher.py:51-55, src/node.py:29,100).  TPU-natively the topology is a
static ``jax.sharding.Mesh``: the "stage" axis is the pipeline chain (the
successor relation is the ``ppermute`` permutation over ICI), and an optional
"data" axis replicates the whole pipeline for batch parallelism.  Multi-host
slices get DCN routing automatically from JAX's global mesh machinery.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

STAGE_AXIS = "stage"
DATA_AXIS = "data"
MODEL_AXIS = "model"


def pipeline_mesh(num_stages: int, data_parallel: int = 1,
                  tensor_parallel: int = 1, devices=None) -> Mesh:
    """Mesh of shape (data, stage[, model]) over the available devices.

    The model (tensor-parallel) axis is innermost — a stage's TP group sits
    on adjacent devices so its per-layer psums ride nearest-neighbor ICI;
    stage neighbors come next for the stage-axis ``ppermute``.
    """
    devices = list(devices if devices is not None else jax.devices())
    need = num_stages * data_parallel * tensor_parallel
    if len(devices) < need:
        raise ValueError(
            f"pipeline needs {need} devices "
            f"({data_parallel} data x {num_stages} stages x "
            f"{tensor_parallel} model) but only {len(devices)} available")
    if tensor_parallel > 1:
        arr = np.array(devices[:need]).reshape(
            data_parallel, num_stages, tensor_parallel)
        return Mesh(arr, (DATA_AXIS, STAGE_AXIS, MODEL_AXIS))
    arr = np.array(devices[:need]).reshape(data_parallel, num_stages)
    return Mesh(arr, (DATA_AXIS, STAGE_AXIS))


def stage_axis_size(mesh: Mesh) -> int:
    return mesh.shape[STAGE_AXIS]
