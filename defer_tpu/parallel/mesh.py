"""Device-mesh construction for the pipeline.

The reference's topology is a runtime-configured linear chain of TCP hosts —
the dispatcher tells each node its successor's IP (reference
src/dispatcher.py:51-55, src/node.py:29,100).  TPU-natively the topology is a
static ``jax.sharding.Mesh``: the "stage" axis is the pipeline chain (the
successor relation is the ``ppermute`` permutation over ICI), and an optional
"data" axis replicates the whole pipeline for batch parallelism.  Multi-host
slices get DCN routing automatically from JAX's global mesh machinery.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

STAGE_AXIS = "stage"
DATA_AXIS = "data"


def pipeline_mesh(num_stages: int, data_parallel: int = 1,
                  devices=None) -> Mesh:
    """Mesh of shape (data_parallel, num_stages) over the available devices.

    Stage neighbors are placed adjacently so the stage-axis ``ppermute``
    rides nearest-neighbor ICI links.
    """
    devices = list(devices if devices is not None else jax.devices())
    need = num_stages * data_parallel
    if len(devices) < need:
        raise ValueError(
            f"pipeline needs {need} devices "
            f"({data_parallel} data x {num_stages} stages) but only "
            f"{len(devices)} available")
    arr = np.array(devices[:need]).reshape(data_parallel, num_stages)
    return Mesh(arr, (DATA_AXIS, STAGE_AXIS))


def stage_axis_size(mesh: Mesh) -> int:
    return mesh.shape[STAGE_AXIS]
