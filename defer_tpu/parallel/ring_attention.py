"""Ring attention: sequence-parallel exact attention over a mesh axis.

Long-context support is absent from the reference (CNN-only workloads —
SURVEY.md §2.3 SP row); it is first-class here.  The sequence axis is sharded
over a ``seq`` mesh axis; each device holds a Q/K/V shard and K/V shards
rotate around the ring via ``lax.ppermute`` (ICI neighbor hops) while a
numerically-stable online-softmax accumulator (flash-attention style: running
max, running denominator, rescaled value accumulator) builds the exact
attention output — memory per device is O(T/N), communication is N-1 ICI
hops of the K/V shard, and the result is bit-for-bit the same math as full
attention up to float reassociation.

The same trick the pipeline engine uses for stages (neighbor ppermute over
ICI) applied to the sequence dimension — both are instances of the
"systolic ring over the mesh" pattern this framework is built on.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..utils.compat import axis_size, shard_map

SEQ_AXIS = "seq"


def _online_block(q, k, v, m, l, acc, scale, mask=None):
    """One block of streaming-softmax attention accumulation.

    q: [B,H,Tq,D]; k,v: [B,H,Tk,D]; m,l: [B,H,Tq]; acc: [B,H,Tq,D].
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if mask is not None:
        s = jnp.where(mask, s, jnp.asarray(-jnp.inf, s.dtype))
    m_blk = jnp.max(s, axis=-1)
    m_new = jnp.maximum(m, m_blk)
    # guard fully-masked rows (m_new = -inf): keep accumulators unchanged
    safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
    p = jnp.exp(s - safe_m[..., None])
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    l_new = l * alpha + jnp.sum(p, axis=-1)
    acc_new = acc * alpha[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return m_new, l_new, acc_new


def ring_attention(q, k, v, *, axis_name: str = SEQ_AXIS,
                   causal: bool = False):
    """Exact attention with K/V rotating around the ``axis_name`` ring.

    Call inside ``shard_map``; q/k/v are the local shards [B, H, Tl, D]
    (sequence dim sharded over the ring).  ``causal`` applies a causal mask
    consistent with the *global* sequence order (shard i holds positions
    [i*Tl, (i+1)*Tl)).
    """
    n = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, h, tl, d = q.shape
    scale = 1.0 / math.sqrt(d)
    perm = [(i, (i + 1) % n) for i in range(n)]

    m0 = jnp.full((b, h, tl), -jnp.inf, q.dtype)
    l0 = jnp.zeros((b, h, tl), q.dtype)
    acc0 = jnp.zeros_like(q)

    q_pos = idx * tl + jnp.arange(tl)

    def block(r, k_r, v_r, m, l, acc):
        # k_r/v_r hold the shard originating at device idx - r
        src = (idx - r) % n
        if causal:
            k_pos = src * tl + jnp.arange(tl)
            mask = q_pos[:, None] >= k_pos[None, :]
            mask = jnp.broadcast_to(mask[None, None], (b, h, tl, tl))
        else:
            mask = None
        return _online_block(q, k_r, v_r, m, l, acc, scale, mask)

    def step(carry, r):
        k_r, v_r, m, l, acc = carry
        m, l, acc = block(r, k_r, v_r, m, l, acc)
        k_r = lax.ppermute(k_r, axis_name, perm)
        v_r = lax.ppermute(v_r, axis_name, perm)
        return (k_r, v_r, m, l, acc), ()

    # n-1 (compute, rotate) steps, then a final compute with no rotation —
    # the last ppermute's result would be discarded, and a scan carry can't
    # be dead-code-eliminated by XLA, so keep it out of the loop
    (k_f, v_f, m, l, acc), _ = lax.scan(
        step, (k, v, m0, l0, acc0), jnp.arange(n - 1))
    m, l, acc = block(n - 1, k_f, v_f, m, l, acc)
    return acc / jnp.maximum(l, jnp.asarray(1e-20, l.dtype))[..., None]


def full_attention(q, k, v, *, causal: bool = False):
    """Reference single-device attention (for equivalence tests).

    ``causal`` uses bottom-right alignment when Tq != Tk (query row i sees
    key positions <= i + Tk - Tq), matching ``flash_attention`` decode
    semantics."""
    d = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(d)
    if causal:
        tq, tk = q.shape[2], k.shape[2]
        q_pos = jnp.arange(tq)[:, None] + (tk - tq)
        mask = q_pos >= jnp.arange(tk)[None, :]
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def sequence_parallel_attention(q, k, v, mesh: Mesh, *,
                                axis_name: str = SEQ_AXIS,
                                causal: bool = False):
    """Convenience wrapper: global [B,H,T,D] arrays in, attention out, with
    the sequence dimension sharded over ``mesh[axis_name]`` and K/V ring-
    rotated over ICI."""
    spec = P(None, None, axis_name, None)
    fn = shard_map(
        functools.partial(ring_attention, axis_name=axis_name, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)
