"""Tensor parallelism: intra-layer (Megatron-style) sharding over a
``"model"`` mesh axis.

The reference has no tensor parallelism — each partition lives wholly on one
node (reference src/dispatcher.py:44-65, one sub-model per IP) — but its
capability frame ("split a model across devices that each hold a piece")
extends naturally to the intra-layer axis on TPU: weight matrices are
sharded across devices, every device computes a partial product, and one
``lax.psum`` over ICI reconstitutes the activation.  This module provides

  * per-op sharding hooks (``Op.tp_shard`` / ``Op.tp_apply``) implemented by
    the matmul-bearing ops (``Dense``, ``TransformerBlock``);
  * :func:`shard_tp_params` — slice a parameter pytree into per-rank shards
    stacked on a leading ``[tp, ...]`` axis for sharded ``device_put``;
  * :func:`tensor_parallel_fn` — a ``shard_map``-wrapped graph forward where
    weights live sharded over the ``model`` axis and activations are
    replicated, XLA inserting the matching ICI collectives.

Sharding scheme (the standard column→row pairing, two psums per
transformer block):

  =============  ==========================  =====================
  parameter      split                       collective
  =============  ==========================  =====================
  Dense.w        rows (input dim)            psum after matmul
  qkv.w / .b     columns, per head group     none (local heads)
  proj.w         rows                        psum before residual
  fc1.w / .b     columns                     none
  fc2.w          rows                        psum before residual
  =============  ==========================  =====================
"""

from __future__ import annotations

from typing import Any

import numpy as np

import jax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..graph.ir import LayerGraph
from ..utils.compat import shard_map
from .mesh import MODEL_AXIS


def tensor_parallel_mesh(tp: int, devices=None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) < tp:
        raise ValueError(f"need {tp} devices, have {len(devices)}")
    return Mesh(np.array(devices[:tp]), (MODEL_AXIS,))


def shard_tp_params(graph: LayerGraph, params: dict[str, Any], tp: int,
                    mesh: Mesh | None = None, axis: str = MODEL_AXIS):
    """Per-rank TP shards of ``params``, stacked on a leading [tp, ...] axis.

    Ops that don't implement ``tp_shard`` are replicated (each rank gets the
    full leaf).  If ``mesh`` is given the result is ``device_put`` with the
    leading axis sharded over ``axis`` so each device materializes only its
    own shard.
    """
    out: dict[str, Any] = {}
    for name, node in graph.nodes.items():
        p = params.get(name)
        if p is None:
            continue
        shards = [node.op.tp_shard(p, tp, r) for r in range(tp)]
        out[name] = jax.tree.map(
            lambda *xs: np.stack([np.asarray(x) for x in xs]), *shards)
    if mesh is not None:
        out = jax.device_put(
            out, NamedSharding(mesh, P(axis)))
    return out


def tensor_parallel_fn(graph: LayerGraph, mesh: Mesh, axis: str = MODEL_AXIS):
    """Jitted TP forward: ``fn(stacked_params, x) -> y``.

    ``stacked_params`` comes from :func:`shard_tp_params`; ``x`` and ``y``
    are replicated across the ``model`` axis, weights stay sharded.
    """
    tp = mesh.shape[axis]

    def local_fn(pstk, x):
        params = jax.tree.map(lambda a: a[0], pstk)  # my rank's shard
        cache = {graph.input_name: x}
        for name in graph.topo_order:
            node = graph.nodes[name]
            xs = [cache[i] for i in node.inputs]
            cache[name] = node.op.tp_apply(params.get(name), *xs,
                                           axis_name=axis, tp=tp)
        return cache[graph.output_name]

    fn = shard_map(local_fn, mesh=mesh, in_specs=(P(axis), P()),
                       out_specs=P(), check_vma=False)
    return jax.jit(fn)
