"""Ulysses-style sequence parallelism: all_to_all head-scatter attention.

The second of the two standard SP schemes (ring attention being the other,
``ring_attention.py``): instead of rotating K/V shards around a ring, two
``lax.all_to_all`` exchanges re-shard the tensors from sequence-sharded
[B, H, T/N, D] to head-sharded [B, H/N, T, D], run ordinary full attention
locally over the complete sequence, and shard back.  Communication is
O(T·D·H/N) per device independent of N hops (vs the ring's N-1 neighbor
hops), so it wins when the head count comfortably exceeds the mesh size and
the fabric provides good all-to-all bandwidth; the ring wins at very long T
(smaller live buffers).  Both produce exact attention.

Requires num_heads % mesh_size == 0; the global sequence must be evenly
sharded.
"""

from __future__ import annotations

import functools

import jax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..utils.compat import axis_size, shard_map
from .ring_attention import SEQ_AXIS, full_attention


def ulysses_attention(q, k, v, *, axis_name: str = SEQ_AXIS,
                      causal: bool = False):
    """Exact attention on sequence-sharded q/k/v via head scatter.

    Call inside ``shard_map``; q/k/v are local shards [B, H, T/N, D].
    Returns the local output shard [B, H, T/N, D].
    """
    n = axis_size(axis_name)
    h = q.shape[1]
    if h % n:
        raise ValueError(f"num_heads={h} not divisible by mesh size {n}")

    def scatter_heads(x):
        # [b, h, tl, d] -> [b, h/n, T, d]: head chunk j goes to device j,
        # received sequence shards concatenate into the full sequence
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    def gather_heads(x):
        # inverse: [b, h/n, T, d] -> [b, h, tl, d]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    qh, kh, vh = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    out = full_attention(qh, kh, vh, causal=causal)
    return gather_heads(out)


def sequence_parallel_attention_ulysses(q, k, v, mesh: Mesh, *,
                                        axis_name: str = SEQ_AXIS,
                                        causal: bool = False):
    """Convenience wrapper: global [B,H,T,D] in, attention out, sequence dim
    sharded over ``mesh[axis_name]`` with all_to_all head exchange."""
    spec = P(None, None, axis_name, None)
    fn = shard_map(
        functools.partial(ulysses_attention, axis_name=axis_name,
                          causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)
