from .partitioner import partition
from .stage import StageSpec
