from .partitioner import fuse_stages, partition
from .stage import StageSpec
