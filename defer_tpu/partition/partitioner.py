"""Model partitioner: layer graph + cut points → ordered StageSpecs.

Equivalent capability to the reference's ``DEFER._partition``
(src/dispatcher.py:27-42), which loops over split-layer names building one
Keras sub-model per segment.  Differences by design:

  * Cut validity is *checked* against articulation analysis instead of being
    a silent caller obligation (reference src/dag_util.py:28 requires the cut
    to be a single tensor but never verifies it).
  * Partitioning is O(V+E) metadata slicing — no graph reconstruction, no
    layer re-invocation (reference re-invokes every layer per partition,
    src/dag_util.py:23-24).
"""

from __future__ import annotations

from ..graph.analysis import auto_cut_points, valid_cut_points
from ..graph.ir import LayerGraph
from .stage import JoinStageSpec, StageSpec


def partition(graph: LayerGraph, cut_points: list[str] | None = None,
              *, num_stages: int | None = None,
              costs: dict[str, float] | None = None,
              objective: str = "quantile",
              cost_model=None) -> list[StageSpec]:
    """Split ``graph`` into ``len(cut_points)+1`` sequential stages.

    Either pass explicit ``cut_points`` (node names, in topological order —
    the analogue of ``partition_layers`` in reference src/dispatcher.py:107)
    or ``num_stages`` for automatic cuts.  The automatic path forwards
    ``costs`` (measured per-node seconds), ``objective``
    ("quantile" greedy — the default — or the exact comm-aware
    "bottleneck" solver) and ``cost_model`` to
    :func:`~defer_tpu.graph.analysis.auto_cut_points`; previously
    ``num_stages`` always fell back to the analytic-FLOP quantile
    heuristic with no way to pass either.
    """
    if cut_points is None:
        if num_stages is None:
            raise ValueError("pass cut_points or num_stages")
        cut_points = auto_cut_points(graph, num_stages, costs=costs,
                                     objective=objective,
                                     cost_model=cost_model)
    elif costs is not None or cost_model is not None:
        raise ValueError("explicit cut_points leave nothing to balance: "
                         "drop costs/cost_model or drop cut_points")

    order = graph.topo_order
    pos = {n: i for i, n in enumerate(order)}
    valid = set(valid_cut_points(graph))
    for c in cut_points:
        if c not in graph.nodes:
            raise ValueError(f"cut point {c!r} is not a node of {graph.name!r}")
        if c not in valid:
            raise ValueError(
                f"cut point {c!r} is not a single-tensor cut: more than one "
                f"tensor crosses the boundary (valid cuts: {sorted(valid)})")
    if any(pos[a] >= pos[b] for a, b in zip(cut_points, cut_points[1:])):
        raise ValueError("cut_points must be in topological order and unique")

    bounds = [graph.input_name] + list(cut_points) + [graph.output_name]
    stages = []
    for s in range(len(cut_points) + 1):
        start, end = bounds[s], bounds[s + 1]
        lo = pos[start] + 1 if start != graph.input_name else 0
        hi = pos[end] + 1
        names = tuple(order[lo:hi])
        stages.append(StageSpec(
            index=s,
            name=f"{graph.name}/stage{s}",
            graph=graph,
            node_names=names,
            input_name=start,
            output_name=end,
            in_spec=graph.out_spec(start),
            out_spec=graph.out_spec(end),
        ))
    return stages


def stage_specs_for_vertices(graph: LayerGraph, vertices) -> list:
    """One stage spec per :class:`~defer_tpu.runtime.topology.TopoVertex`
    — the DAG partitioner.

    Where :func:`partition` slices the graph at a linear cut list, a
    topology names each vertex's node slice explicitly (branch bodies
    are not contiguous in the full graph's topo order), so this is a
    checked projection, not a search: every vertex becomes a
    :class:`StageSpec` (or :class:`JoinStageSpec` when it merges P
    paths), validated to evaluate a well-formed closure — every node's
    inputs must come from the vertex's own slice or its seed tensors.
    """
    order = {n: i for i, n in enumerate(graph.topo_order)}
    specs = []
    for v in vertices:
        have = set(v.inputs) | set(v.nodes)
        for n in v.nodes:
            if n not in graph.nodes:
                raise ValueError(f"vertex {v.vid}: unknown node {n!r}")
            missing = [i for i in graph.nodes[n].inputs if i not in have]
            if missing:
                raise ValueError(
                    f"vertex {v.vid}: node {n!r} needs {missing} which "
                    f"neither the vertex slice nor its seed inputs "
                    f"{list(v.inputs)} provide")
        nodes = tuple(sorted(v.nodes, key=order.__getitem__))
        if not nodes or nodes[-1] != v.output:
            raise ValueError(f"vertex {v.vid}: output {v.output!r} must "
                             f"be the slice's final node")
        name = f"{graph.name}/{v.label}"
        if v.join >= 2:
            specs.append(JoinStageSpec(
                index=v.vid, name=name, graph=graph, node_names=nodes,
                input_names=tuple(v.inputs), output_name=v.output,
                in_specs=tuple(graph.out_spec(i) for i in v.inputs),
                out_spec=graph.out_spec(v.output)))
        else:
            specs.append(StageSpec(
                index=v.vid, name=name, graph=graph, node_names=nodes,
                input_name=v.inputs[0], output_name=v.output,
                in_spec=graph.out_spec(v.inputs[0]),
                out_spec=graph.out_spec(v.output)))
    return specs


def fuse_stages(stages: "list[StageSpec]", hop_tiers: "list[str]"
                ) -> "tuple[list[StageSpec], list[list[int]]]":
    """Collapse every ``device``-tier hop: adjacent stages that land on
    one device compile into a SINGLE jit stage program instead of paying
    a frame + dispatch per boundary (the MPK mega-kernelization
    direction, PAPERS.md).

    Because a stage is a contiguous graph slice, fusing stages ``k`` and
    ``k+1`` is exactly re-partitioning WITHOUT the cut between them —
    the merged slice exports/compiles as one StableHLO program, so the
    hop (its frame, its queue, its codec) ceases to exist rather than
    being made cheap.

    ``hop_tiers`` has one entry per inter-stage hop (len =
    ``len(stages) - 1``); every ``"device"`` entry fuses its two sides.
    Returns ``(fused_stages, groups)`` where ``groups[j]`` lists the
    ORIGINAL stage indices merged into fused stage ``j`` — callers remap
    per-stage attributes (hop codecs, replica counts) through it.
    """
    if len(hop_tiers) != len(stages) - 1:
        raise ValueError(f"{len(stages)} stages need {len(stages) - 1} "
                         f"hop tiers, got {len(hop_tiers)}")
    groups: list[list[int]] = [[0]]
    for k, tier in enumerate(hop_tiers):
        if tier == "device":
            groups[-1].append(k + 1)
        else:
            groups.append([k + 1])
    if len(groups) == len(stages):
        return list(stages), groups  # nothing to fuse
    keep = [stages[g[-1]].output_name for g in groups[:-1]]
    return partition(stages[0].graph, keep), groups
