"""StageSpec: one pipeline stage = a contiguous slice of the layer graph.

The TPU-native replacement for the reference's per-partition
``tf.keras.Model`` (built by ``construct_model``, reference
src/dag_util.py:27-31, and shipped over TCP as JSON+weights, reference
src/dispatcher.py:44-65).  A StageSpec is pure metadata + a pure function;
nothing is serialized or shipped — placement happens via sharding at
compile time.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax

from ..graph.ir import LayerGraph, ShapeSpec


@dataclasses.dataclass(frozen=True)
class StageSpec:
    index: int
    name: str
    graph: LayerGraph
    node_names: tuple[str, ...]   # topo-ordered nodes evaluated by this stage
    input_name: str               # upstream node (or graph input) feeding it
    output_name: str
    in_spec: ShapeSpec
    out_spec: ShapeSpec

    def fn(self, stage_params: dict[str, Any], x: jax.Array, *,
           tp_axis: str | None = None, tp: int = 1) -> jax.Array:
        """Pure batched forward for this stage (optionally TP-sharded)."""
        return self.graph.apply(stage_params, x, start=self.input_name,
                                upto=self.output_name,
                                node_names=self.node_names,
                                tp_axis=tp_axis, tp=tp)

    def select_params(self, params: dict[str, Any]) -> dict[str, Any]:
        """Subset of the full parameter pytree owned by this stage."""
        return {n: params[n] for n in self.node_names if n in params}

    def tp_shard_params(self, params: dict[str, Any], tp: int,
                        rank: int) -> dict[str, Any]:
        """Rank ``rank``'s TP shard of this stage's parameters."""
        sp = self.select_params(params)
        return {n: self.graph.nodes[n].op.tp_shard(sp[n], tp, rank)
                for n in sp}

    def __repr__(self):
        return (f"StageSpec({self.index}: {self.input_name} -> "
                f"{self.output_name}, {len(self.node_names)} nodes, "
                f"in={self.in_spec.shape}, out={self.out_spec.shape})")
