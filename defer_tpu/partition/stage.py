"""StageSpec: one pipeline stage = a contiguous slice of the layer graph.

The TPU-native replacement for the reference's per-partition
``tf.keras.Model`` (built by ``construct_model``, reference
src/dag_util.py:27-31, and shipped over TCP as JSON+weights, reference
src/dispatcher.py:44-65).  A StageSpec is pure metadata + a pure function;
nothing is serialized or shipped — placement happens via sharding at
compile time.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax

from ..graph.ir import LayerGraph, ShapeSpec


def buffer_footprint(stages, *, microbatch: int = 1, itemsize: int = 4,
                     wire: str = "buffer") -> dict:
    """Homogeneous transfer-buffer geometry for a stage list.

    The single source of truth for what every SPMD hop carries —
    ``SpmdPipeline``, the CLI partition table, and the benchmark suite all
    derive from this so reported waste always matches the deployed buffer:
    ``buf_elems`` (max stage boundary, padded to the int8 block size under
    ``wire="int8"``), ``hop_utilization`` (hop k = stage k's output), and
    ``bytes_per_hop`` (int8: ~1 byte/value + one f32 scale per block).
    """
    buf = max([s.in_spec.size for s in stages]
              + [s.out_spec.size for s in stages])
    if wire == "int8":
        from ..ops.quant import BLOCK
        buf = -(-buf // BLOCK) * BLOCK
        hop_bytes = microbatch * (buf + 4 * (buf // BLOCK))
    else:
        hop_bytes = buf * microbatch * itemsize
    return {
        "buf_elems": buf,
        "hop_utilization": [s.out_spec.size / buf for s in stages],
        "bytes_per_hop": hop_bytes,
    }


@dataclasses.dataclass(frozen=True)
class StageSpec:
    index: int
    name: str
    graph: LayerGraph
    node_names: tuple[str, ...]   # topo-ordered nodes evaluated by this stage
    input_name: str               # upstream node (or graph input) feeding it
    output_name: str
    in_spec: ShapeSpec
    out_spec: ShapeSpec

    def fn(self, stage_params: dict[str, Any], x: jax.Array, *,
           tp_axis: str | None = None, tp: int = 1) -> jax.Array:
        """Pure batched forward for this stage (optionally TP-sharded)."""
        return self.graph.apply(stage_params, x, start=self.input_name,
                                upto=self.output_name,
                                node_names=self.node_names,
                                tp_axis=tp_axis, tp=tp)

    def select_params(self, params: dict[str, Any]) -> dict[str, Any]:
        """Subset of the full parameter pytree owned by this stage."""
        return {n: params[n] for n in self.node_names if n in params}

    def tp_shard_params(self, params: dict[str, Any], tp: int,
                        rank: int) -> dict[str, Any]:
        """Rank ``rank``'s TP shard of this stage's parameters."""
        sp = self.select_params(params)
        return {n: self.graph.nodes[n].op.tp_shard(sp[n], tp, rank)
                for n in sp}

    def tp_unshard_params(self, rank_params: "list[dict[str, Any]]"
                          ) -> dict[str, Any]:
        """Inverse of :meth:`tp_shard_params`: all ranks' stage shards ->
        the stage's full parameters (op-specific reassembly)."""
        return {n: self.graph.nodes[n].op.tp_unshard(
                    [rp[n] for rp in rank_params])
                for n in rank_params[0]}

    def __repr__(self):
        return (f"StageSpec({self.index}: {self.input_name} -> "
                f"{self.output_name}, {len(self.node_names)} nodes, "
                f"in={self.in_spec.shape}, out={self.out_spec.shape})")


@dataclasses.dataclass(frozen=True)
class JoinStageSpec:
    """A multi-input pipeline stage: the join of a branched stage graph.

    Where :class:`StageSpec` resumes the graph from ONE boundary tensor,
    a join stage resumes from ``P`` of them — its first node is the
    graph's merge op (Concat/Add), whose inputs arrive as separate
    frames from the parallel branch sub-pipelines (in the merge op's
    input order, which is the transport's path order —
    ``transport/branch.py``).  Everything downstream of the merge up to
    the stage's output rides in the same program, so the join costs one
    dispatch like any other stage.
    """

    index: int
    name: str
    graph: LayerGraph
    node_names: tuple[str, ...]
    input_names: tuple[str, ...]  # P seed tensors, in merge-input order
    output_name: str
    in_specs: tuple[ShapeSpec, ...]
    out_spec: ShapeSpec

    @property
    def in_spec(self) -> ShapeSpec:
        """First input's spec (single-input compatibility surface —
        ``buffer_footprint`` and friends size buffers off the fattest
        boundary, which :attr:`in_specs` callers handle explicitly)."""
        return self.in_specs[0]

    @property
    def num_inputs(self) -> int:
        return len(self.input_names)

    def fn(self, stage_params: dict[str, Any], *xs: jax.Array) -> jax.Array:
        if len(xs) != len(self.input_names):
            raise ValueError(f"join stage {self.index} takes "
                             f"{len(self.input_names)} inputs, got "
                             f"{len(xs)}")
        return self.graph.apply(stage_params, upto=self.output_name,
                                node_names=self.node_names,
                                seeds=dict(zip(self.input_names, xs)))

    def select_params(self, params: dict[str, Any]) -> dict[str, Any]:
        return {n: params[n] for n in self.node_names if n in params}

    def __repr__(self):
        return (f"JoinStageSpec({self.index}: "
                f"[{','.join(self.input_names)}] -> {self.output_name}, "
                f"{len(self.node_names)} nodes)")
