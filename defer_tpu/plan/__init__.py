"""Comm-aware pipeline planning: bottleneck-minimizing cuts, per-hop
codec selection, telemetry-driven replanning.

The quantile heuristic (``graph.analysis.auto_cut_points``) balances
per-stage compute and ignores transport entirely; after the overlap PR
the steady-state cost of a deployed chain is ``max_k max(compute_k,
comm_k)``, so a cut at a fat-activation boundary can make the wire the
bottleneck no matter how balanced the FLOPs are.  This package solves
the real objective:

* :mod:`~defer_tpu.plan.cost` — :class:`StageCostModel`: roofline (or
  measured) per-node compute seconds + per-cut, per-codec comm seconds,
  with host codec calibration (:func:`calibrate_codecs`).
* :mod:`~defer_tpu.plan.solver` — exact DP (and a binary-search
  variant) minimizing the bottleneck, choosing the cheapest codec per
  hop, plus :func:`sweep_stages` over stage counts.
* :mod:`~defer_tpu.plan.replan` — correct the model with a live
  ``MetricsRegistry`` snapshot / chain ``stats`` and emit a plan diff.

See ``docs/PLANNER.md`` for the model and the recurrence.
"""

from .calibrate import (CalibratedConstants, CalibrationError,
                        fit_constants, fit_from_stats,
                        hop_telemetry_from_stats, measure_memory_bw,
                        predict_stage_service_s)
from .cost import (CodecSpec, DEFAULT_CODECS, TIER_CODECS, StageCostModel,
                   bench_codec_instance, bench_codec_spec,
                   calibrate_codecs, max_batch_within_budget,
                   stage_ms_at_batch)
from .dag import (DagPlan, best_linear_plan, brute_force_dag,
                  dag_plan_from_json, solve_dag)
from .replan import (ReplanResult, corrected_cost_model,
                     cost_model_from_plan, measured_stage_seconds, replan)
from .solver import (Plan, ReplicatedPlan, brute_force,
                     brute_force_replicated, evaluate_cuts,
                     plan_from_json, solve, solve_replicated,
                     sweep_nodes, sweep_stages)

__all__ = [
    "CodecSpec", "DEFAULT_CODECS", "TIER_CODECS", "StageCostModel",
    "bench_codec_instance", "bench_codec_spec", "calibrate_codecs",
    "Plan", "solve", "evaluate_cuts", "sweep_stages", "brute_force",
    "ReplicatedPlan", "solve_replicated", "brute_force_replicated",
    "sweep_nodes", "plan_from_json",
    "DagPlan", "solve_dag", "brute_force_dag", "dag_plan_from_json",
    "best_linear_plan",
    "ReplanResult", "replan", "measured_stage_seconds",
    "corrected_cost_model", "cost_model_from_plan",
    "max_batch_within_budget", "stage_ms_at_batch",
    "CalibratedConstants", "CalibrationError", "fit_constants",
    "fit_from_stats", "hop_telemetry_from_stats", "measure_memory_bw",
    "predict_stage_service_s",
]
