"""Online calibration: fit the planner's constants from live telemetry.

The cost model prices hops with guessed constants — codec throughputs
from :data:`~defer_tpu.plan.cost.DEFAULT_CODECS`, memory/host-sync
bandwidths from order-of-magnitude defaults — while the runtime measures
the real thing on every frame: per-channel encode/decode histograms,
per-stage ``host_sync`` histograms, per-frame send times, byte counters.
This module closes that loop:

1. :func:`hop_telemetry_from_stats` reshapes a ``ChainDispatcher.stats``
   reply (or ``ClusterView`` rows) into per-hop telemetry records —
   stage ``k``'s outbound hop pairs stage ``k``'s encode/host-sync/send
   histograms with stage ``k+1``'s decode histogram (decode is measured
   at the RECEIVER).
2. :func:`fit_constants` turns those records into a versioned
   :class:`CalibratedConstants` artifact: per-codec encode/decode
   throughputs, ``host_sync_bw_s``, ``ici_bw_s``, wire ``link_bw_s``
   (all bytes/seconds regressions over the summaries' exact
   ``sum``/``count`` fields), plus a memcpy micro-bench for the
   ``local``/``shm`` memory-bandwidth term.  Degenerate inputs —
   zero-byte hops, histograms with fewer than ``min_samples`` samples —
   are rejected LOUDLY (:class:`CalibrationError`), never silently
   fitted: a bandwidth regressed from one sample is a lie with a
   version number.
3. :meth:`CalibratedConstants.apply` overlays the fitted constants on
   any :class:`~defer_tpu.plan.cost.StageCostModel`; the artifact also
   round-trips through plan JSON (``describe()`` carries the constants,
   ``cost_model_from_plan`` restores them), so a replan seeded from a
   calibrated plan keeps scoring with measured numbers.

:func:`predict_stage_service_s` is the audit half: the per-stage service
prediction ALIGNED with what the runtime measures — stage ``k`` =
``max(compute_k, decode(hop k-1), encode(hop k))`` with CODEC-ONLY
enc/dec parts, because the live service estimate
(``ClusterView._service_ms``) is the max of the infer / per-channel
decode / per-channel encode p50s, none of which include the host-sync
round-trip (measured separately).  ``obs/capacity.py``'s drift auditor
scores this prediction against measurement continuously.

Why a codec the model has never seen still calibrates: the fit keys
fitted specs by the DEPLOYED codec name (``dsleep10+raw`` included).  A
default-constants model prices an unknown name via the ``raw`` fallback
— exactly the failure mode that makes uncalibrated predictions wrong on
any chain whose codecs do real work.
"""

from __future__ import annotations

import copy
import dataclasses
import json
import time

import numpy as np

from ..graph.ir import LayerGraph
from .cost import (DEFAULT_CODECS, TIER_CODECS, CodecSpec, StageCostModel)

#: artifact schema identifier; bump on incompatible layout changes
SCHEMA = "defer_tpu.calibration.v1"

#: a histogram with fewer samples than this cannot anchor a bandwidth
#: fit (one compile-warm outlier would BE the estimate)
DEFAULT_MIN_SAMPLES = 8


class CalibrationError(ValueError):
    """A fit was asked to regress from degenerate telemetry (zero-byte
    hop, under-sampled histogram).  Loud on purpose: a silently-skipped
    hop would leave a default constant masquerading as calibrated."""


# ---------------------------------------------------------------------------
# telemetry records
# ---------------------------------------------------------------------------

def _summ(row, key) -> dict:
    s = row.get(key)
    return s if isinstance(s, dict) else {"count": 0}


def _delta(now: dict, base: dict | None) -> dict:
    """Window-bound a cumulative summary: subtract an earlier snapshot's
    exact ``count``/``sum`` so the fit reflects the CURRENT regime, not
    the lifetime average (cold-start/compile samples included forever).
    Percentiles cannot be subtracted; the fit only consumes
    count/sum, which can."""
    if not base or not base.get("count"):
        return dict(now)
    n = int(now.get("count", 0)) - int(base.get("count", 0))
    if n <= 0:
        return {"count": 0}
    return {"count": n,
            "sum": float(now.get("sum", 0.0)) - float(base.get("sum", 0.0))}


def hop_telemetry_from_stats(graph: LayerGraph, cuts: list[str],
                             stats: list[dict], *, batch: int = 1,
                             baseline: list[dict] | None = None
                             ) -> list[dict]:
    """Per-hop telemetry records from a ``ChainDispatcher.stats`` reply.

    Hop ``k`` (stage ``k`` -> ``k+1``) joins stage ``k``'s outbound-side
    histograms (``encode_latency_s``, ``host_sync_s``, ``tx_s``) with
    stage ``k+1``'s ``decode_latency_s`` — decode runs at the receiver.
    Raw boundary bytes come from the graph (``out_spec(cut)`` at
    ``batch``), NOT from the tx byte counters, which are process-wide
    registry totals (per-stage only in multi-process runs).

    Replicated stages contribute one merged record per hop (replica
    summaries pooled by count/sum).  ``baseline`` is an earlier stats
    reply from the same chain: when given, every summary is
    window-bounded by delta (see :func:`_delta`) so calibration scores
    the current regime.
    """
    def pool(rows, key, base_rows):
        out = {"count": 0, "sum": 0.0}
        for r in rows:
            b = None
            if base_rows:
                b = next((_summ(br, key) for br in base_rows
                          if br.get("replica") == r.get("replica")), None)
            s = _delta(_summ(r, key), b)
            if s.get("count"):
                out["count"] += int(s["count"])
                out["sum"] += float(s.get("sum", 0.0))
        return out if out["count"] else {"count": 0}

    by_stage: dict[int, list[dict]] = {}
    for row in stats:
        if isinstance(row, dict) and row.get("stage") is not None:
            by_stage.setdefault(int(row["stage"]), []).append(row)
    base_by_stage: dict[int, list[dict]] = {}
    for row in baseline or ():
        if isinstance(row, dict) and row.get("stage") is not None:
            base_by_stage.setdefault(int(row["stage"]), []).append(row)

    hops = []
    for k, cut in enumerate(cuts):
        tx_rows = by_stage.get(k) or []
        rx_rows = by_stage.get(k + 1) or []
        if not tx_rows:
            continue
        spec = graph.out_spec(cut)
        raw = int(spec.size) * spec.dtype.itemsize * max(1, int(batch))
        tb, rb = base_by_stage.get(k), base_by_stage.get(k + 1)
        hops.append({
            "cut": cut,
            "stage": k,
            "raw_bytes": raw,
            "codec": tx_rows[0].get("codec"),
            "tier": tx_rows[0].get("tier") or "tcp",
            "enc_s": pool(tx_rows, "encode_latency_s", tb),
            "dec_s": pool(rx_rows, "decode_latency_s", rb),
            "host_sync_s": pool(tx_rows, "host_sync_s", tb),
            "tx_s": pool(tx_rows, "tx_s", tb),
        })
    return hops


# ---------------------------------------------------------------------------
# the artifact
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class CalibratedConstants:
    """A versioned bundle of measured planner constants.

    Every field carries a ``provenance`` entry —
    ``{"method": "measured"|"bench"|"prior", "samples": n, "bytes": b}``
    — so a consumer can tell a regression over 10k frames from a default
    that merely survived the fit untouched."""

    schema: str = SCHEMA
    gen: str = "unknown"
    created_unix: float = 0.0
    local_bw_s: float | None = None
    host_sync_bw_s: float | None = None
    ici_bw_s: float | None = None
    link_bw_s: float | None = None
    codecs: dict[str, CodecSpec] = dataclasses.field(default_factory=dict)
    provenance: dict[str, dict] = dataclasses.field(default_factory=dict)

    def to_json(self) -> dict:
        return {
            "schema": self.schema, "gen": self.gen,
            "created_unix": round(self.created_unix, 3),
            "local_bw_s": self.local_bw_s,
            "host_sync_bw_s": self.host_sync_bw_s,
            "ici_bw_s": self.ici_bw_s,
            "link_bw_s": self.link_bw_s,
            "codecs": {n: dataclasses.asdict(c)
                       for n, c in sorted(self.codecs.items())},
            "provenance": {k: dict(v)
                           for k, v in sorted(self.provenance.items())},
        }

    @classmethod
    def from_json(cls, doc: dict) -> "CalibratedConstants":
        schema = doc.get("schema")
        if schema != SCHEMA:
            raise CalibrationError(
                f"unknown calibration schema {schema!r} (expected {SCHEMA})")
        codecs = {n: CodecSpec(**c)
                  for n, c in (doc.get("codecs") or {}).items()}
        return cls(schema=SCHEMA, gen=doc.get("gen", "unknown"),
                   created_unix=float(doc.get("created_unix", 0.0)),
                   local_bw_s=doc.get("local_bw_s"),
                   host_sync_bw_s=doc.get("host_sync_bw_s"),
                   ici_bw_s=doc.get("ici_bw_s"),
                   link_bw_s=doc.get("link_bw_s"),
                   codecs=codecs,
                   provenance=dict(doc.get("provenance") or {}))

    def save(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.to_json(), f, indent=2, sort_keys=True)
            f.write("\n")

    @classmethod
    def load(cls, path: str) -> "CalibratedConstants":
        with open(path, encoding="utf-8") as f:
            return cls.from_json(json.load(f))

    def apply(self, cost: StageCostModel) -> StageCostModel:
        """A shallow copy of ``cost`` with every fitted constant
        overlaid (unfitted fields keep the model's own values); fitted
        codec specs MERGE over the model's table, so deployed codec
        names the analytic table never heard of become priceable."""
        other = copy.copy(cost)
        if self.local_bw_s:
            other.local_bw_s = float(self.local_bw_s)
        if self.host_sync_bw_s:
            other.host_sync_bw_s = float(self.host_sync_bw_s)
        if self.ici_bw_s:
            other.ici_bw_s = float(self.ici_bw_s)
        if self.link_bw_s:
            other.link_bw_s = float(self.link_bw_s)
        if self.codecs:
            other.codecs = {**cost.codecs, **self.codecs}
        return other


# ---------------------------------------------------------------------------
# fitting
# ---------------------------------------------------------------------------

def measure_memory_bw(*, nbytes: int = 1 << 24, reps: int = 3) -> float:
    """Host memory bandwidth (bytes/s) from a memcpy micro-bench — the
    constant behind the ``local`` tier's wire term and half the ``shm``
    ring's write-in/read-out pair.  Min over ``reps`` timed copies after
    a warm round, same protocol as the codec micro-bench."""
    src = np.ones(max(nbytes, 1 << 16), dtype=np.uint8)
    dst = np.empty_like(src)
    np.copyto(dst, src)  # warm (page faults / first touch)
    best = float("inf")
    for _ in range(max(1, reps)):
        t0 = time.perf_counter()
        np.copyto(dst, src)
        best = min(best, time.perf_counter() - t0)
    return src.nbytes / max(best, 1e-9)


def _bw_fit(pairs) -> tuple[float | None, int, int]:
    """Aggregate bandwidth over (raw_bytes, summary) pairs:
    ``sum(bytes_i * count_i) / sum(seconds_i)`` — the count-weighted
    regression through the origin the exact sum/count fields support.
    Returns (bw or None, samples, bytes)."""
    num = den = 0.0
    samples = 0
    for raw, summ in pairs:
        n = int(summ.get("count", 0))
        s = float(summ.get("sum", 0.0))
        if n <= 0 or s <= 0:
            continue
        num += raw * n
        den += s
        samples += n
    if den <= 0 or samples == 0:
        return None, 0, 0
    return num / den, samples, int(num)


def _check_hop(hop: dict, min_samples: int) -> None:
    raw = int(hop.get("raw_bytes", 0))
    if raw <= 0:
        raise CalibrationError(
            f"zero-byte hop at cut {hop.get('cut')!r}: a bandwidth "
            f"cannot be regressed from 0 bytes")
    for key in ("enc_s", "dec_s", "host_sync_s", "tx_s"):
        summ = hop.get(key)
        if not isinstance(summ, dict):
            continue
        n = int(summ.get("count", 0))
        # count == 0 is legitimate absence (an ici hop records no
        # host_sync — that is the tier working); 0 < n < min_samples is
        # an under-sampled histogram and must not anchor a fit
        if 0 < n < min_samples:
            raise CalibrationError(
                f"hop at cut {hop.get('cut')!r}: {key} has only {n} "
                f"sample(s) (< {min_samples}); run longer or lower "
                f"min_samples explicitly")


def fit_constants(hops: list[dict], *,
                  min_samples: int = DEFAULT_MIN_SAMPLES,
                  gen: str = "unknown",
                  prior: StageCostModel | None = None,
                  bench_memory: bool = True) -> CalibratedConstants:
    """Fit :class:`CalibratedConstants` from per-hop telemetry records.

    Each record (see :func:`hop_telemetry_from_stats`) carries
    ``raw_bytes`` (the boundary tensor's bytes), the deployed ``codec``
    and ``tier``, and cumulative summaries ``enc_s`` / ``dec_s`` /
    ``host_sync_s`` / ``tx_s`` (``{"count", "sum"}`` at least).  Fits:

    * per-codec ``encode_bytes_per_s`` / ``decode_bytes_per_s`` — keyed
      by the DEPLOYED codec name, count-weighted over every hop that
      rode that codec; ratio/lossy carried from ``prior``'s table (or
      :data:`DEFAULT_CODECS`) when the name is known, else 1.0 /
      name-prefix heuristic (wire-byte ratios need per-channel byte
      counters, which the registry only attributes per-process);
    * ``host_sync_bw_s`` — one-pass bandwidth from the ``host_sync``
      histograms (the producing loop's timed ``np.asarray`` D2H; the
      model's 2x term then prices the symmetric H2D re-upload at the
      same rate — docs/PLANNER.md spells out the protocol);
    * ``ici_bw_s`` — from device-resident hops' per-frame send times
      (``tx_s`` on ``tier == "ici"`` hops: the d2d put is the send);
    * ``link_bw_s`` — from wire hops' send-minus-encode residual
      (``tx_s`` prices encode+send; subtract the encode sum);
    * ``local_bw_s`` — a memcpy micro-bench on THIS host
      (``bench_memory=False`` keeps the prior — e.g. when fitting on a
      machine that will not run the chain).

    A constant with no usable telemetry keeps the ``prior``'s value with
    ``{"method": "prior"}`` provenance.  Degenerate records raise
    :class:`CalibrationError` (see :func:`_check_hop`).
    """
    if not hops:
        raise CalibrationError("no hop telemetry records to fit from")
    min_samples = max(2, int(min_samples))
    for hop in hops:
        _check_hop(hop, min_samples)

    prior_codecs = dict(prior.codecs) if prior is not None \
        else dict(DEFAULT_CODECS)
    out = CalibratedConstants(gen=gen, created_unix=time.time())
    prov = out.provenance

    # -- per-codec throughputs (wire hops only) -----------------------------
    enc_pairs: dict[str, list] = {}
    dec_pairs: dict[str, list] = {}
    for hop in hops:
        codec = hop.get("codec")
        if not codec or codec in TIER_CODECS \
                or (hop.get("tier") or "tcp") != "tcp":
            continue
        enc_pairs.setdefault(codec, []).append(
            (hop["raw_bytes"], hop.get("enc_s") or {}))
        dec_pairs.setdefault(codec, []).append(
            (hop["raw_bytes"], hop.get("dec_s") or {}))
    for codec in sorted(set(enc_pairs) | set(dec_pairs)):
        enc_bw, enc_n, enc_b = _bw_fit(enc_pairs.get(codec, ()))
        dec_bw, dec_n, dec_b = _bw_fit(dec_pairs.get(codec, ()))
        base = prior_codecs.get(codec)
        if enc_bw is None and dec_bw is None:
            continue  # hop deployed the codec but no frames moved yet
        out.codecs[codec] = CodecSpec(
            name=codec,
            ratio=base.ratio if base else 1.0,
            encode_bytes_per_s=enc_bw if enc_bw is not None
            else (base.encode_bytes_per_s if base else 8e9),
            decode_bytes_per_s=dec_bw if dec_bw is not None
            else (base.decode_bytes_per_s if base else 8e9),
            lossy=base.lossy if base else codec.startswith("bf"))
        prov[f"codec.{codec}"] = {
            "method": "measured", "samples": enc_n + dec_n,
            "bytes": enc_b + dec_b}

    # -- host_sync bandwidth ------------------------------------------------
    hs_bw, hs_n, hs_b = _bw_fit(
        (h["raw_bytes"], h.get("host_sync_s") or {}) for h in hops)
    if hs_bw is not None:
        out.host_sync_bw_s = hs_bw
        prov["host_sync_bw_s"] = {"method": "measured",
                                  "samples": hs_n, "bytes": hs_b}
    elif prior is not None:
        out.host_sync_bw_s = prior.host_sync_bw_s
        prov["host_sync_bw_s"] = {"method": "prior", "samples": 0,
                                  "bytes": 0}

    # -- ici bandwidth ------------------------------------------------------
    ici_bw, ici_n, ici_b = _bw_fit(
        (h["raw_bytes"], h.get("tx_s") or {})
        for h in hops if (h.get("tier") or "tcp") == "ici")
    if ici_bw is not None:
        out.ici_bw_s = ici_bw
        prov["ici_bw_s"] = {"method": "measured", "samples": ici_n,
                            "bytes": ici_b}
    elif prior is not None:
        out.ici_bw_s = prior.ici_bw_s
        prov["ici_bw_s"] = {"method": "prior", "samples": 0, "bytes": 0}

    # -- wire bandwidth -----------------------------------------------------
    # tx_s prices encode+send per frame; the send residual over the wire
    # bytes is the link estimate.  The tx_s histogram is process-wide
    # (registry), so this is trustworthy in multi-process runs and a
    # same-rate approximation in-process; negative residuals (encode
    # dominated) yield no fit rather than a wild one.
    num = den = 0.0
    link_n = 0
    for h in hops:
        if (h.get("tier") or "tcp") != "tcp":
            continue
        tx, enc = h.get("tx_s") or {}, h.get("enc_s") or {}
        n = min(int(tx.get("count", 0)), int(enc.get("count", 0)))
        if n <= 0:
            continue
        send_sum = float(tx.get("sum", 0.0)) \
            - float(enc.get("sum", 0.0)) * (int(tx.get("count", 0)) / max(
                1, int(enc.get("count", 0))))
        if send_sum <= 0:
            continue
        spec = out.codecs.get(h.get("codec")) \
            or prior_codecs.get(h.get("codec"))
        ratio = spec.ratio if spec else 1.0
        num += (h["raw_bytes"] / max(ratio, 1e-9)) * n
        den += send_sum
        link_n += n
    if den > 0 and link_n:
        out.link_bw_s = num / den
        prov["link_bw_s"] = {"method": "measured", "samples": link_n,
                             "bytes": int(num)}
    elif prior is not None:
        out.link_bw_s = prior.link_bw_s
        prov["link_bw_s"] = {"method": "prior", "samples": 0, "bytes": 0}

    # -- local / shm memory bandwidth ---------------------------------------
    if bench_memory:
        out.local_bw_s = measure_memory_bw()
        prov["local_bw_s"] = {"method": "bench", "samples": 1,
                              "bytes": 1 << 24}
    elif prior is not None:
        out.local_bw_s = prior.local_bw_s
        prov["local_bw_s"] = {"method": "prior", "samples": 0, "bytes": 0}
    return out


def fit_from_stats(graph: LayerGraph, cuts: list[str], stats: list[dict],
                   *, batch: int = 1, gen: str = "unknown",
                   prior: StageCostModel | None = None,
                   baseline: list[dict] | None = None,
                   min_samples: int = DEFAULT_MIN_SAMPLES,
                   bench_memory: bool = True) -> CalibratedConstants:
    """One-call convenience: stats reply -> telemetry records -> fit."""
    hops = hop_telemetry_from_stats(graph, cuts, stats, batch=batch,
                                    baseline=baseline)
    return fit_constants(hops, min_samples=min_samples, gen=gen,
                         prior=prior, bench_memory=bench_memory)


# ---------------------------------------------------------------------------
# measurement-aligned prediction (the audit half)
# ---------------------------------------------------------------------------

def codec_only_parts(cost: StageCostModel, cut: str, codec: str
                     ) -> tuple[float, float]:
    """(encode, decode) seconds of ``codec`` at ``cut`` EXCLUDING the
    host-sync halves — aligned with the per-channel encode/decode
    histograms, which time exactly the codec work.  Tier pseudo-codecs
    do no codec work on either side.  An unknown codec name falls back
    to the ``raw`` spec — the documented failure mode of an
    uncalibrated model pricing a deployed codec it has no row for."""
    if codec in TIER_CODECS:
        return 0.0, 0.0
    spec = cost.codecs.get(codec) or cost.codecs.get("raw") \
        or next(iter(cost.codecs.values()))
    enc, _, dec = spec.comm_parts(cost.cut_bytes(cut), cost.link_bw_s)
    return enc, dec


def predict_stage_service_s(graph: LayerGraph, cuts: list[str],
                            hop_codecs: list[str],
                            cost: StageCostModel) -> list[float]:
    """Per-stage predicted SERVICE seconds, aligned with the live
    estimate: stage ``k`` is rate-bound by the slowest of its three
    overlapped phase threads — inbound decode of hop ``k-1``, infer,
    outbound encode of hop ``k`` — so the prediction is their max, with
    codec-only enc/dec parts (see :func:`codec_only_parts`).

    This deliberately differs from ``Plan.stage_cost_s``, which charges
    hop ``k``'s WHOLE comm (encode+wire+decode+host_sync) to stage
    ``k``: an audit must attribute work to the process that measures
    it, or a decode-heavy codec shows up as drift on the wrong stage."""
    if len(hop_codecs) != len(cuts):
        raise ValueError(f"{len(cuts)} cuts but {len(hop_codecs)} "
                         f"hop codecs")
    order = graph.topo_order
    pos = {n: i for i, n in enumerate(order)}
    bounds = [0] + [pos[c] + 1 for c in cuts] + [len(order)]
    out = []
    for k in range(len(bounds) - 1):
        names = order[bounds[k]:bounds[k + 1]]
        service = cost.compute_seconds(names)
        if k > 0:
            _, dec = codec_only_parts(cost, cuts[k - 1], hop_codecs[k - 1])
            service = max(service, dec)
        if k < len(cuts):
            enc, _ = codec_only_parts(cost, cuts[k], hop_codecs[k])
            service = max(service, enc)
        out.append(service)
    return out
