"""Stage cost model: per-node compute seconds + per-cut comm seconds.

The planner's view of the hardware.  Two halves:

* **Compute** — an analytic roofline per node: ``max(flops / peak,
  bytes_moved / hbm_bw)`` with the public per-generation peaks from
  ``utils/hw.py``.  Pass ``node_costs`` (measured seconds, e.g. from
  ``utils.profiling.measured_node_costs``) to replace the analytic model
  with what the backend actually does — the FLOP model under-weights
  bandwidth-bound ops, and a CPU backend shares none of the TPU ratios.

* **Comm** — per valid cut, per codec: the boundary tensor's bytes
  (``graph.out_spec(cut)``, dtype itemsize, batch) through
  ``encode + wire + decode``::

      comm = raw/enc_Bps  +  (raw/ratio)/link_bw  +  raw/dec_Bps

  Codec ratio and encode/decode throughput come from a
  :class:`CodecSpec` table — analytic defaults below, or calibrated on
  THIS host by :func:`calibrate_codecs` (the same measurement loop as
  ``scripts/bench_codec.py``, on a synthetic post-ReLU-like payload).
  Link bandwidth defaults to the chip generation's one-way ICI figure
  (``hw.ici_bandwidth``) and is overridable (``--link-bw``) for DCN /
  ethernet hops, where the codec trade flips in favor of compressing.

The model is deliberately slack about absolute accuracy — the planner
only needs the *relative* weights right, and ``plan/replan.py`` corrects
the compute side with live telemetry.
"""

from __future__ import annotations

import copy
import dataclasses
import time

import numpy as np

from ..graph.ir import LayerGraph
from ..utils import hw


@dataclasses.dataclass(frozen=True)
class CodecSpec:
    """What the comm model needs to know about one hop codec."""

    name: str
    ratio: float              #: raw bytes / wire bytes (>= 1 compresses)
    encode_bytes_per_s: float  #: host encode throughput on RAW bytes
    decode_bytes_per_s: float  #: host decode throughput on RAW bytes
    lossy: bool = False

    def comm_parts(self, raw_bytes: int, link_bw: float
                   ) -> tuple[float, float, float]:
        """(encode, wire, decode) seconds for one boundary tensor —
        split out because stage replication parallelizes the encode and
        decode sides independently (``plan/solver.py``): the hop OUT of
        an R-replica stage encodes on R processes at once, the hop INTO
        one decodes on R, while the wire term serializes at whichever
        single endpoint the fan terminates on."""
        enc = raw_bytes / self.encode_bytes_per_s \
            if self.encode_bytes_per_s > 0 else 0.0
        dec = raw_bytes / self.decode_bytes_per_s \
            if self.decode_bytes_per_s > 0 else 0.0
        wire = (raw_bytes / max(self.ratio, 1e-9)) / link_bw \
            if link_bw > 0 else 0.0
        return enc, wire, dec

    def comm_seconds(self, raw_bytes: int, link_bw: float) -> float:
        """encode + wire + decode seconds for one boundary tensor."""
        return sum(self.comm_parts(raw_bytes, link_bw))


#: analytic defaults (order-of-magnitude host-edge numbers; calibrate on
#: the deployment host for real planning).  ``raw`` pays only a memcpy.
DEFAULT_CODECS: dict[str, CodecSpec] = {
    "raw": CodecSpec("raw", ratio=1.0, encode_bytes_per_s=8e9,
                     decode_bytes_per_s=8e9),
    "lzb": CodecSpec("lzb", ratio=1.3, encode_bytes_per_s=2e8,
                     decode_bytes_per_s=5e8),
    "bf8": CodecSpec("bf8", ratio=3.9, encode_bytes_per_s=1.5e8,
                     decode_bytes_per_s=2.5e8, lossy=True),
    "bf16": CodecSpec("bf16", ratio=2.0, encode_bytes_per_s=1.5e8,
                      decode_bytes_per_s=2.5e8, lossy=True),
}

#: transport-tier PSEUDO-codecs (docs/TRANSPORT.md tier matrix): the comm
#: model of a colocated hop.  These never enter the per-hop codec argmin
#: (every hop would trivially "choose" them) — they are selected by the
#: hop-tier map (``StageCostModel(hop_tiers=...)``) and REPLACE the codec
#: trade on hops the deployment declares colocated:
#:
#: * ``local`` — same process, in-memory channel: zero encode/decode
#:   (the array passes by reference), wire term = one memory-bandwidth
#:   pass over the boundary bytes (the queue handoff's cache/allocator
#:   cost — ``DEFAULT_LOCAL_BW_S``, override with ``local_bw_s=``).
#: * ``shm`` — same host, separate processes, shared-memory ring
#:   (``transport/shm.py``): zero encode/decode, wire term = TWO
#:   memory-bandwidth passes over the boundary bytes (the write-in +
#:   read-out memcpy pair) — costlier than ``local``, decades cheaper
#:   than any TCP hop, so the ladder's preference order (local over
#:   shm over tcp) falls out of the model.
#: * ``ici`` — same mesh, device-resident (``transport/ici.py``): the
#:   activation never touches the host — zero encode/decode, zero
#:   host-sync, wire term = the boundary bytes over the chip
#:   interconnect (``hw.ici_bandwidth``, override with ``ici_bw_s=`` /
#:   ``--ici-bw``).  At TPU ICI rates this sits between ``device``
#:   (free) and ``local``.
#: * ``device`` — the stages fuse into one jit program
#:   (``partition.fuse_stages``): the hop does not exist; ~0 seconds.
#:
#: Every OTHER tier additionally pays the ``host_sync`` term (below):
#: the per-hop D2H materialization + H2D re-upload the runtime's
#: compute loops perform around any non-device-resident hop — the cost
#: the ``local`` pseudo-codec used to omit silently, and the one the
#: ici tier removes.  With it the model's preference order is
#: principled: device <= ici <= local <= shm <= tcp.
TIER_CODECS: dict[str, CodecSpec] = {
    "ici": CodecSpec("ici", ratio=1.0, encode_bytes_per_s=0.0,
                     decode_bytes_per_s=0.0),
    "local": CodecSpec("local", ratio=1.0, encode_bytes_per_s=0.0,
                       decode_bytes_per_s=0.0),
    "shm": CodecSpec("shm", ratio=1.0, encode_bytes_per_s=0.0,
                     decode_bytes_per_s=0.0),
    "device": CodecSpec("device", ratio=1.0, encode_bytes_per_s=0.0,
                        decode_bytes_per_s=0.0),
}

#: host memory bandwidth for the ``local`` pseudo-codec's wire term —
#: one DRAM-class pass over the boundary tensor (order-of-magnitude;
#: the planner needs relative weights, and ~10 GB/s keeps a colocated
#: hop 2-3 decades under any TCP hop without rounding it to free).
DEFAULT_LOCAL_BW_S = 1e10

#: host-sync bandwidth: the D2H + H2D transfer pair every
#: non-device-resident hop pays around its transport (the producing
#: loop's ``np.asarray``, the consuming program's re-upload).  Same
#: DRAM-class order of magnitude as :data:`DEFAULT_LOCAL_BW_S`;
#: calibratable from the runtime's per-stage ``host_sync_s``
#: histograms (docs/OBSERVABILITY.md).
DEFAULT_HOST_SYNC_BW_S = 1e10


def _check_hop_tiers(graph: LayerGraph,
                     hop_tiers: dict[str, str] | None, *,
                     valid=None) -> dict[str, str]:
    """Validate a hop-tier map: known tier names AND real cut-point
    keys — a misspelled cut silently scoring as tcp would make the
    planner model a topology the caller never declared (same loud-miss
    policy as the constructor's ``node_costs`` check).

    ``valid`` overrides the cut namespace: the DAG planner passes
    ``graph.analysis.dag_cut_points`` so branch-internal hops — real
    deployable boundaries once branches run as their own sub-pipelines
    — validate too, under the same loud-miss policy."""
    if not hop_tiers:
        return {}
    bad = [t for t in hop_tiers.values() if t not in ("tcp", *TIER_CODECS)]
    if bad:
        raise ValueError(f"unknown hop tiers {bad}; "
                         f"use tcp|{'|'.join(TIER_CODECS)}")
    if valid is None:
        from ..graph.analysis import valid_cut_points
        valid = valid_cut_points(graph)
    valid = set(valid)
    missing = [c for c in hop_tiers if c not in valid]
    if missing:
        raise ValueError(
            f"hop_tiers name cuts that are not valid cut points of "
            f"{graph.name!r}: {missing[:5]}")
    return dict(hop_tiers)


def bench_codec_instance(codec, payload: np.ndarray, *,
                         reps: int = 3) -> tuple[float, float, float]:
    """(ratio, encode_bytes_per_s, decode_bytes_per_s) for one codec
    object on ``payload``: min over ``reps`` timed rounds after a warm
    round — the shared measurement core of ``scripts/bench_codec.py``
    and :func:`calibrate_codecs`."""
    nbytes = payload.nbytes
    enc = codec.encode(payload)  # warm (native build / first-touch)
    t_enc = t_dec = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        enc = codec.encode(payload)
        t_enc = min(t_enc, time.perf_counter() - t0)
    codec.decode(enc, payload.shape, payload.dtype)  # warm
    for _ in range(reps):
        t0 = time.perf_counter()
        codec.decode(enc, payload.shape, payload.dtype)
        t_dec = min(t_dec, time.perf_counter() - t0)
    enc_len = enc.nbytes if isinstance(enc, memoryview) else len(enc)
    return (nbytes / max(enc_len, 1), nbytes / max(t_enc, 1e-9),
            nbytes / max(t_dec, 1e-9))


def bench_codec_spec(name: str, payload: np.ndarray, *,
                     reps: int = 3) -> CodecSpec:
    """Measure one wire codec (by its ``transport.framed`` name) on
    ``payload``; see :func:`bench_codec_instance`."""
    from ..transport.framed import _codec
    ratio, enc_bps, dec_bps = bench_codec_instance(
        _codec(name), payload, reps=reps)
    return CodecSpec(name=name, ratio=ratio, encode_bytes_per_s=enc_bps,
                     decode_bytes_per_s=dec_bps,
                     lossy=name.startswith("bf"))


def calibrate_codecs(names=("raw", "lzb", "bf8", "bf16"), *,
                     nbytes: int = 1 << 20, zero_fraction: float = 0.5,
                     reps: int = 3, seed: int = 0) -> dict[str, CodecSpec]:
    """Micro-bench every codec in ``names`` on THIS host.

    The payload is a ReLU-like activation (``zero_fraction`` zeros,
    otherwise half-normal) — the regime the hop codecs actually see, and
    the one where lzb's ratio depends on sparsity.  ~1 MB keeps the whole
    calibration under a second per codec even on the NumPy fallback.
    """
    rng = np.random.default_rng(seed)
    x = rng.standard_normal(max(nbytes // 4, 256)).astype(np.float32)
    x[rng.random(x.size) < zero_fraction] = 0.0
    x = np.abs(x)
    return {n: bench_codec_spec(n, x, reps=reps) for n in names}


class StageCostModel:
    """Per-node compute seconds and per-cut comm seconds for a graph.

    ``node_costs`` (name -> measured seconds) overrides the analytic
    roofline; otherwise ``peak_flops_s`` / ``hbm_bw_s`` anchor it (both
    default from the detected chip generation, falling back to v5e
    numbers off-TPU so relative weights stay sane).  ``link_bw_s`` is the
    hop bandwidth in bytes/s; ``codecs`` the candidate
    :class:`CodecSpec` table per hop.

    ``hop_tiers`` (cut name -> ``"local"``/``"shm"``/``"device"``,
    anything absent = ``"tcp"``) declares which boundaries the
    deployment colocates: those hops cost their :data:`TIER_CODECS` pseudo-codec
    instead of the cheapest wire codec, so cut placement EXPLOITS
    colocation (a fat boundary is free to cross on a fused hop) instead
    of modeling every boundary as a TCP hop.  ``local_bw_s`` sets the
    ``local`` tier's memory-bandwidth wire term
    (:data:`DEFAULT_LOCAL_BW_S`).
    """

    def __init__(self, graph: LayerGraph, *, batch: int = 1,
                 gen: str | None = None,
                 peak_flops_s: float | None = None,
                 hbm_bw_s: float | None = None,
                 link_bw_s: float | None = None,
                 codecs: dict[str, CodecSpec] | None = None,
                 node_costs: dict[str, float] | None = None,
                 lossless_only: bool = False,
                 hop_tiers: dict[str, str] | None = None,
                 local_bw_s: float | None = None,
                 ici_bw_s: float | None = None,
                 host_sync_bw_s: float | None = None):
        self.graph = graph
        self.batch = max(int(batch), 1)
        if gen is None:
            gen = self._detect_gen()
        self.gen = gen
        # unknown generations fall back to v5e so the analytic model
        # still ranks nodes instead of dividing by zero; absolute
        # seconds are then only as good as the fallback (calibrate or
        # pass node_costs for real numbers)
        ref = gen if hw.peak_flops(gen) > 0 else "v5e"
        self.peak_flops_s = peak_flops_s or hw.peak_flops(ref)
        self.hbm_bw_s = hbm_bw_s or hw.hbm_bandwidth(ref)
        self.link_bw_s = link_bw_s or hw.ici_bandwidth(ref)
        self.codecs = dict(codecs) if codecs is not None \
            else dict(DEFAULT_CODECS)
        if lossless_only:
            self.codecs = {n: c for n, c in self.codecs.items()
                           if not c.lossy} or {"raw": DEFAULT_CODECS["raw"]}
        if node_costs is not None:
            missing = [n for n in graph.topo_order if n not in node_costs]
            if missing:
                raise ValueError(
                    f"node_costs missing nodes: {missing[:5]}...")
        self.node_costs = dict(node_costs) if node_costs else None
        self.hop_tiers = _check_hop_tiers(graph, hop_tiers)
        self.local_bw_s = local_bw_s or DEFAULT_LOCAL_BW_S
        #: device-to-device interconnect bandwidth for the ``ici``
        #: pseudo-codec's wire term (defaults to the chip generation's
        #: one-way ICI figure, like ``link_bw_s``; override for slower
        #: meshes the same way ``--link-bw`` overrides the wire; 0 =
        #: model the d2d wire as free, same convention as host_sync)
        self.ici_bw_s = hw.ici_bandwidth(ref) if ici_bw_s is None \
            else float(ici_bw_s)
        #: D2H/H2D bandwidth for the per-hop host_sync term every
        #: non-device-resident tier pays (0 = model the sync as free —
        #: the same convention as a zero link bandwidth)
        self.host_sync_bw_s = DEFAULT_HOST_SYNC_BW_S \
            if host_sync_bw_s is None else float(host_sync_bw_s)

    @staticmethod
    def _detect_gen() -> str:
        try:
            import jax
            return hw.identify_chip(jax.devices()[0])
        except Exception:  # noqa: BLE001 — no backend: analytic fallback
            return "unknown"

    # -- compute -----------------------------------------------------------

    def node_seconds(self, name: str) -> float:
        """Roofline (or measured) seconds for one node at ``batch``.

        ``node_costs`` entries are taken AS-IS: measure them at the same
        batch you plan for (``measured_node_costs(graph, params,
        batch=...)`` does) — only the analytic roofline scales by
        ``batch`` itself."""
        if self.node_costs is not None:
            return self.node_costs[name]
        from ..graph.analysis import node_flops
        g = self.graph
        node = g.nodes[name]
        flops = node_flops(g, name) * self.batch
        moved = sum(g.out_spec(i).size * g.out_spec(i).dtype.itemsize
                    for i in node.inputs)
        moved += node.out_spec.size * node.out_spec.dtype.itemsize
        moved *= self.batch
        t_flops = flops / self.peak_flops_s if self.peak_flops_s > 0 else 0.0
        t_mem = moved / self.hbm_bw_s if self.hbm_bw_s > 0 else 0.0
        return max(t_flops, t_mem)

    def compute_seconds(self, names) -> float:
        return sum(self.node_seconds(n) for n in names)

    # -- comm --------------------------------------------------------------

    def cut_bytes(self, cut: str) -> int:
        """Raw bytes of the boundary tensor crossing ``cut`` at ``batch``."""
        spec = self.graph.out_spec(cut)
        return spec.size * spec.dtype.itemsize * self.batch

    def hop_tier(self, cut: str) -> str:
        """Declared transport tier of the hop at ``cut`` (default tcp)."""
        return self.hop_tiers.get(cut, "tcp")

    def with_hop_tiers(self, hop_tiers: dict[str, str] | None, *,
                       valid_cuts=None) -> "StageCostModel":
        """A shallow copy scoring hops under ``hop_tiers`` — how
        ``solve(..., hop_tiers=...)`` threads a deployment's tier map
        through without mutating the caller's model.  ``valid_cuts``
        widens the key namespace (the DAG planner passes the stage-graph
        cut set, branch-internal hops included)."""
        other = copy.copy(self)
        other.hop_tiers = _check_hop_tiers(self.graph, hop_tiers,
                                           valid=valid_cuts)
        return other

    def host_sync_seconds(self, cut: str) -> float:
        """The per-hop host round-trip every non-device-resident
        transport pays: the producing stage's D2H materialization
        (``np.asarray`` in the compute loop) plus the consuming
        program's H2D re-upload — two passes over the boundary bytes at
        ``host_sync_bw_s``.  The ``ici`` tier keeps the activation
        device-resident and the ``device`` tier has no hop at all, so
        only tcp/local/shm hops carry this term; it is what makes the
        tier ordering device <= ici <= local <= shm <= tcp principled
        instead of accidental."""
        return 2 * self.cut_bytes(cut) / self.host_sync_bw_s \
            if self.host_sync_bw_s > 0 else 0.0

    def _tier_parts(self, cut: str, tier: str
                    ) -> tuple[float, float, float]:
        """(encode, wire, decode) seconds of a colocated hop: zero
        codec work on both sides; ``ici`` pays one interconnect pass
        (device-to-device, no host term), ``local`` one memory-
        bandwidth pass over the boundary bytes plus the host_sync
        round-trip, ``shm`` two passes (the ring's write-in + read-out
        memcpy pair) plus host_sync, ``device`` (a fused program)
        nothing."""
        if tier == "device":
            return 0.0, 0.0, 0.0
        n = self.cut_bytes(cut)
        if tier == "ici":
            wire = n / self.ici_bw_s if self.ici_bw_s > 0 else 0.0
            return 0.0, wire, 0.0
        if tier == "shm":
            n *= 2
        enc, wire, dec = TIER_CODECS["local"].comm_parts(
            n, self.local_bw_s)
        return enc, wire + self.host_sync_seconds(cut), dec

    def comm_seconds(self, cut: str, codec: str) -> float:
        if codec in TIER_CODECS:
            return sum(self._tier_parts(cut, codec))
        return self.codecs[codec].comm_seconds(self.cut_bytes(cut),
                                               self.link_bw_s) \
            + self.host_sync_seconds(cut)

    def best_codec(self, cut: str) -> tuple[str, float]:
        """Cheapest (codec name, comm seconds) for the hop at ``cut``.

        A cut whose declared tier is ``local``/``device`` skips the wire
        codec argmin entirely — the tier's pseudo-codec IS the hop's
        transport, and its name lands in the plan's ``hop_codecs`` so a
        plan row shows which hops ride the fast path."""
        tier = self.hop_tier(cut)
        if tier in TIER_CODECS:
            return tier, sum(self._tier_parts(cut, tier))
        return min(((n, self.comm_seconds(cut, n)) for n in self.codecs),
                   key=lambda kv: kv[1])

    def comm_parts(self, cut: str, codec: str
                   ) -> tuple[float, float, float]:
        """(encode, wire, decode) seconds for ``codec`` at ``cut``.
        Wire codecs carry the host_sync round-trip split across the
        encode (D2H materialization) and decode (H2D re-upload) sides —
        each half parallelizes with its side's replicas, exactly like
        the codec work it sits next to in the compute loops."""
        if codec in TIER_CODECS:
            return self._tier_parts(cut, codec)
        enc, wire, dec = self.codecs[codec].comm_parts(
            self.cut_bytes(cut), self.link_bw_s)
        h = self.host_sync_seconds(cut) / 2
        return enc + h, wire, dec + h

    def comm_parts_deployed(self, cut: str, codec: str
                            ) -> tuple[float, float, float]:
        """:meth:`comm_parts` for a DEPLOYED codec name: a wire codec
        the table has no row for is priced as ``raw`` instead of
        raising.  This is the audit/rescoring path (``evaluate_cuts``'s
        ``hop_codecs`` pin): a deployment can run codecs the analytic
        table never heard of, and scoring what actually runs must not
        crash — the raw fallback IS the uncalibrated model's documented
        failure mode, which calibration (fitted specs keyed by the
        deployed name) removes."""
        if codec in TIER_CODECS or codec in self.codecs:
            return self.comm_parts(cut, codec)
        spec = self.codecs.get("raw") or next(iter(self.codecs.values()))
        enc, wire, dec = spec.comm_parts(self.cut_bytes(cut),
                                         self.link_bw_s)
        h = self.host_sync_seconds(cut) / 2
        return enc + h, wire, dec + h

    def best_codec_replicated(self, cut: str, r_up: int, r_down: int
                              ) -> tuple[str, float]:
        """Cheapest (codec, effective seconds) for the hop at ``cut``
        when the upstream stage runs ``r_up`` replicas and the
        downstream ``r_down``: the encode side is paid by r_up processes
        in parallel, the decode side by r_down, and the wire serializes
        at the fan's single endpoint — ``enc/r_up + wire + dec/r_down``.

        Tier interaction: a colocated tier only applies when NEITHER
        side is replicated (the runtime's fan paths always ride tcp — a
        fan-out cannot hand one live array to R processes); replicated
        hops fall back to the wire-codec argmin.
        """
        tier = self.hop_tier(cut)
        if tier in TIER_CODECS and max(r_up, 1) == 1 \
                and max(r_down, 1) == 1:
            return tier, sum(self._tier_parts(cut, tier))
        best_name, best = None, float("inf")
        for n in self.codecs:
            enc, wire, dec = self.comm_parts(cut, n)
            s = enc / max(r_up, 1) + wire + dec / max(r_down, 1)
            if s < best:
                best_name, best = n, s
        return best_name, best

    def at_batch(self, batch: int) -> "StageCostModel":
        """A shallow copy scoring the SAME graph at a different frame
        batch — the serving front door's latency-budget query
        (:func:`max_batch_within_budget`) sweeps this.  Analytic costs
        scale themselves; measured ``node_costs`` (taken as-is at the
        model's own batch) are scaled LINEARLY from it — an honest
        first-order approximation (per-sample cost rarely shrinks with
        batch on a saturated stage, so the query errs toward smaller,
        latency-safer batches when the real curve is sublinear)."""
        batch = max(1, int(batch))
        other = copy.copy(self)
        if self.node_costs is not None:
            scale = batch / self.batch
            other.node_costs = {k: v * scale
                                for k, v in self.node_costs.items()}
        other.batch = batch
        return other

    def describe(self) -> dict:
        d = {
            "gen": self.gen, "batch": self.batch,
            "peak_flops_s": self.peak_flops_s, "hbm_bw_s": self.hbm_bw_s,
            "link_bw_s": self.link_bw_s,
            # every non-device-resident hop pays the host round-trip,
            # so its bandwidth travels with every plan (a replan seeded
            # from plan JSON must keep scoring it)
            "host_sync_bw_s": self.host_sync_bw_s,
            "node_costs": "measured" if self.node_costs else "roofline",
            "codecs": {n: dataclasses.asdict(c)
                       for n, c in self.codecs.items()},
            # the tier bandwidths travel unconditionally (not only when
            # hop_tiers is set): a CALIBRATED model's constants must
            # survive the plan-JSON roundtrip even when the plan it
            # seeds later declares tiers the original model never had
            "local_bw_s": self.local_bw_s,
            "ici_bw_s": self.ici_bw_s,
        }
        if self.hop_tiers:
            d["hop_tiers"] = dict(sorted(self.hop_tiers.items()))
        return d


# -- latency-budget queries (serving front door) ----------------------------

def stage_ms_at_batch(graph: LayerGraph, cuts: list[str],
                      cost: StageCostModel, batch: int) -> list[float]:
    """Per-stage effective milliseconds (max of compute and hop comm) of
    the ``cuts`` partition at frame ``batch`` — the planner's
    ``stage_effective_ms`` re-evaluated at a candidate microbatch width.
    The continuous-batching scheduler reads its per-stage latency budget
    off this curve (docs/SERVING.md)."""
    from .solver import evaluate_cuts
    plan = evaluate_cuts(graph, list(cuts), cost.at_batch(batch))
    return [s * 1e3 for s in plan.stage_cost_s]


def max_batch_within_budget(graph: LayerGraph, cuts: list[str],
                            cost: StageCostModel, budget_ms: float, *,
                            cap: int = 256) -> int:
    """Largest frame batch whose SLOWEST stage stays within
    ``budget_ms`` — how ``defer_tpu serve`` sizes its dynamic
    microbatches from the planner's cost model instead of a guessed
    constant.  Monotone search (stage time never shrinks with batch
    under this model): geometric probe then bisection.  Always >= 1:
    a budget no batch can meet degrades to latency-optimal singles
    rather than refusing to serve.
    """
    if budget_ms <= 0:
        return 1

    def worst_ms(b: int) -> float:
        return max(stage_ms_at_batch(graph, cuts, cost, b))

    if worst_ms(1) > budget_ms:
        return 1
    lo, hi = 1, 2
    while hi <= cap and worst_ms(hi) <= budget_ms:
        lo, hi = hi, hi * 2
    hi = min(hi, cap + 1)
    while lo + 1 < hi:
        mid = (lo + hi) // 2
        if worst_ms(mid) <= budget_ms:
            lo = mid
        else:
            hi = mid
    return lo
