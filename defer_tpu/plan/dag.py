"""Critical-path-aware planner for DAG-shaped (branch-parallel) pipelines.

The chain solver (``plan/solver.py``) can only cut a branching model at
its articulation points, so everything between two articulations — an
inception block's parallel branches, a branched MoE layer's experts —
lands inside ONE stage, serialized.  "The TensorFlow Partitioning and
Scheduling Problem: It's the Critical Path!" (PAPERS.md) makes the
argument this module implements: for a branching graph the right plan
shape mirrors the graph — parallel branches become concurrent stages —
and the right accounting follows the stage GRAPH, not a flattened chain.

The solved :class:`DagPlan` is a stage graph (``topology`` in its JSON,
the schema ``runtime/topology.py`` deploys):

* each trunk run of nodes is a chain of stages, cut by the same
  bottleneck DP as the linear solver;
* each parallelized fork/join region (``graph.analysis.branch_regions``)
  becomes: a broadcast hop out of the fork stage, one concurrent
  sub-chain per branch (cut independently at the branch's own internal
  cut points), and a join stage that merges all P paths and runs the
  graph's merge op;
* per-stage cost stays ``max(compute, comm)``; the plan reports BOTH
  graph-level aggregates: ``bottleneck_s`` — the max over stage
  vertices, the steady-state period of the pipelined stream — and
  ``critical_path_s`` — the longest root-to-sink path through the
  stage graph, the per-sample latency.  Branch-parallelism shrinks
  both: the region's vertices each hold one branch instead of the sum
  of all of them.

The solver enumerates which regions to parallelize (linear stays the
fallback whenever the node budget is tight or branching never pays),
then allocates the node budget across the independent chain components
(trunk segments and branches) by bisecting the bottleneck over the
per-component DP tables — cuts are chosen per branch independently,
exactly as the independence structure allows.  Objective order:
minimize the bottleneck, tie-break on the critical path, then on node
count.  ``brute_force_dag`` is the exhaustive oracle the property
tests cross-check.
"""

from __future__ import annotations

import dataclasses
import itertools

from ..graph.analysis import (BranchRegion, branch_regions,
                              dag_cut_points, segment_cut_points,
                              valid_cut_points)
from ..graph.ir import LayerGraph
from .cost import TIER_CODECS, StageCostModel
from .solver import _solve_dp

#: kept in sync with ``runtime.topology.TOPOLOGY_FORMAT`` (the planner
#: must stay importable without the runtime's jax-heavy package init)
TOPOLOGY_FORMAT = "defer_tpu.topology.v1"

_EPS = 1e-12


@dataclasses.dataclass
class DagVertex:
    """One stage vertex of a solved stage graph, with its predictions."""

    vid: int
    nodes: tuple[str, ...]
    inputs: tuple[str, ...]
    output: str
    next: tuple[int, ...]
    fan: str = "unicast"          #: "unicast" | "broadcast"
    join: int = 0                 #: >= 2: merges that many paths
    branch: int | None = None     #: path index inside its region
    codec: str = "raw"            #: outbound hop codec ("-" on the exit)
    compute_s: float = 0.0
    comm_s: float = 0.0           #: outbound hop seconds

    @property
    def cost_s(self) -> float:
        return max(self.compute_s, self.comm_s)

    @property
    def label(self) -> str:
        base = f"stage{self.vid}"
        return base if self.branch is None else f"{base}.b{self.branch}"


@dataclasses.dataclass
class DagPlan:
    """A solved branch-parallel stage graph with its predictions."""

    graph_name: str
    vertices: list[DagVertex]
    objective: str
    cost: dict
    parallel_regions: list[dict]   #: [{"fork", "join", "paths"}]

    @property
    def num_stages(self) -> int:
        return len(self.vertices)

    @property
    def num_nodes(self) -> int:
        return len(self.vertices)

    @property
    def bottleneck_s(self) -> float:
        return max(v.cost_s for v in self.vertices)

    @property
    def bottleneck_vertex(self) -> int:
        costs = [v.cost_s for v in self.vertices]
        return costs.index(max(costs))

    @property
    def critical_path_s(self) -> float:
        """Longest root-to-sink path through the stage graph (per-sample
        latency); on a pure chain this is simply the sum of stage
        costs."""
        cp: dict[int, float] = {}
        for v in reversed(self.vertices):
            nxt = max((cp[n] for n in v.next), default=0.0)
            cp[v.vid] = v.cost_s + nxt
        return cp[self.vertices[0].vid] if self.vertices else 0.0

    def predicted_throughput_per_s(self, batch: int = 1) -> float:
        b = self.bottleneck_s
        return batch / b if b > 0 else 0.0

    def topology_json(self) -> dict:
        return {"format": TOPOLOGY_FORMAT,
                "vertices": [{
                    "id": v.vid, "nodes": list(v.nodes),
                    "inputs": list(v.inputs), "output": v.output,
                    "next": list(v.next), "fan": v.fan, "join": v.join,
                    "branch": v.branch,
                    "codec": v.codec if v.codec != "-" else "raw",
                } for v in self.vertices]}

    def to_json(self) -> dict:
        return {
            "graph": self.graph_name,
            "objective": self.objective,
            "num_stages": self.num_stages,
            "num_nodes": self.num_nodes,
            "labels": [v.label for v in self.vertices],
            "stage_compute_ms": [round(v.compute_s * 1e3, 6)
                                 for v in self.vertices],
            "hop_comm_ms": [round(v.comm_s * 1e3, 6)
                            for v in self.vertices],
            "stage_cost_ms": [round(v.cost_s * 1e3, 6)
                              for v in self.vertices],
            "hop_codecs": [v.codec for v in self.vertices],
            "bottleneck_ms": round(self.bottleneck_s * 1e3, 6),
            "bottleneck_stage": self.bottleneck_vertex,
            "critical_path_ms": round(self.critical_path_s * 1e3, 6),
            "parallel_regions": list(self.parallel_regions),
            "topology": self.topology_json(),
            "cost_model": self.cost,
        }


def dag_plan_from_json(doc: dict) -> DagPlan:
    """Rebuild a :class:`DagPlan` from ``to_json`` output (accepts a
    whole ``plan --dag --json`` document)."""
    doc = doc.get("dag_plan", doc.get("plan", doc))
    topo = doc["topology"]
    comp = [v / 1e3 for v in doc["stage_compute_ms"]]
    comm = [v / 1e3 for v in doc["hop_comm_ms"]]
    vs = []
    for d, c, h, codec in zip(topo["vertices"], comp, comm,
                              doc.get("hop_codecs")
                              or [v.get("codec", "raw")
                                  for v in topo["vertices"]]):
        vs.append(DagVertex(
            vid=int(d["id"]), nodes=tuple(d["nodes"]),
            inputs=tuple(d["inputs"]), output=d["output"],
            next=tuple(d["next"]), fan=d.get("fan", "unicast"),
            join=int(d.get("join", 0)),
            branch=None if d.get("branch") is None else int(d["branch"]),
            codec=codec, compute_s=c, comm_s=h))
    return DagPlan(graph_name=doc.get("graph", ""), vertices=vs,
                   objective=doc.get("objective", "critical_path"),
                   cost=doc.get("cost_model", {}),
                   parallel_regions=list(doc.get("parallel_regions", [])))


# -- component machinery -----------------------------------------------------


@dataclasses.dataclass
class _Component:
    """One independently-cuttable chain of the stage graph: a trunk
    segment (between forced fork cuts) or a branch body."""

    kind: str                   #: "trunk" | "branch"
    nodes: list[str]
    cuts: list[str]             #: internal cut candidates, topo order
    edge_comm: float            #: fixed outbound-hop seconds (final stage)
    edge_codec: str
    region: BranchRegion | None = None
    path: int | None = None     #: branch path index
    # tables (filled by _build_tables)
    cum: list[float] = dataclasses.field(default_factory=list)
    total: float = 0.0
    comm: list[float] = dataclasses.field(default_factory=list)
    codec_of: list[str] = dataclasses.field(default_factory=list)

    @property
    def max_stages(self) -> int:
        return len(self.cuts) + 1

    def partition(self, m: int) -> tuple[list[int], float]:
        """(chosen cut indices, bottleneck incl. the fixed edge hop)
        for exactly ``m`` stages."""
        if m == 1:
            return [], max(self.total, self.edge_comm)
        chosen = _solve_dp(self.cum, self.total, self.comm, m)
        return chosen, self.evaluate(chosen)

    def evaluate(self, chosen: list[int]) -> float:
        bounds = [0.0] + [self.cum[i] for i in chosen] + [self.total]
        segs = [bounds[k + 1] - bounds[k] for k in range(len(chosen) + 1)]
        worst = max(max(s, 0.0) for s in segs)
        for k, i in enumerate(chosen):
            worst = max(worst, self.comm[i])
        return max(worst, self.edge_comm)


def _fork_comm(cost: StageCostModel, fork: str, paths: int
               ) -> tuple[str, float]:
    """Cheapest (codec, seconds) for the broadcast hop out of a fork:
    the P copies encode on P parallel channel threads and decode on P
    branch processes, but the WIRE serializes at the fork's endpoint —
    ``enc + P*wire + dec``."""
    best_name, best = None, float("inf")
    for n in cost.codecs:
        enc, wire, dec = cost.comm_parts(fork, n)
        s = enc + paths * wire + dec
        if s < best:
            best_name, best = n, s
    return best_name, best


def _validate_dag_tiers(graph: LayerGraph, hop_tiers: dict | None,
                        regions: list[BranchRegion]) -> None:
    """Stage-graph hop-tier policy: keys must name stage-graph cut
    points (checked by ``with_hop_tiers(valid_cuts=...)``), and a
    colocated (local/device) claim may not touch a fan boundary — a
    region's fork (the broadcast) or a branch output (a labeled join
    path): the ordered branch machinery is wire-framed by design, same
    rule the linear runtime applies to replicated hops."""
    if not hop_tiers:
        return
    fan_cuts = {}
    for r in regions:
        fan_cuts.setdefault(r.fork, f"fork of the {r.join} region")
        for b in r.branches:
            if not b.empty:
                fan_cuts.setdefault(
                    b.out, f"branch output into the {r.join} join")
    for cut, tier in hop_tiers.items():
        if tier in TIER_CODECS and cut in fan_cuts:
            raise ValueError(
                f"hop_tiers[{cut!r}] = {tier!r}, but that cut is the "
                f"{fan_cuts[cut]}: branch fan-out/join hops are "
                f"wire-framed by design and cannot be colocated (drop "
                f"the tier claim or plan without --dag)")


def _components_for(graph: LayerGraph, cost: StageCostModel,
                    node_s: dict[str, float],
                    chosen: list[BranchRegion]) -> list[_Component]:
    """The independent chain components of one topology candidate:
    trunk segments split at each chosen region's fork, plus every
    non-empty branch of the chosen regions."""
    branch_of = {}
    for r in chosen:
        for n in r.branch_nodes:
            branch_of[n] = r
    forks = {r.fork for r in chosen}
    linear_valid = set(valid_cut_points(graph))

    trunk = [n for n in graph.topo_order if n not in branch_of]
    segments: list[list[str]] = [[]]
    for n in trunk:
        segments[-1].append(n)
        if n in forks:
            segments.append([])
    if not segments[-1]:
        raise ValueError("internal: fork with no following trunk node")

    comps: list[_Component] = []
    by_fork = {r.fork: r for r in chosen}
    for i, seg in enumerate(segments):
        last = seg[-1]
        if last in by_fork:
            r = by_fork[last]
            codec, comm = _fork_comm(cost, r.fork, r.width)
        elif i == len(segments) - 1:
            codec, comm = "-", 0.0  # result hop: cut-independent
        else:
            raise AssertionError("trunk segment ends mid-graph")
        comps.append(_Component(
            kind="trunk", nodes=seg,
            cuts=[n for n in seg[:-1] if n in linear_valid],
            edge_comm=comm, edge_codec=codec))
        if last in by_fork:
            r = by_fork[last]
            for p, br in enumerate(r.branches):
                if br.empty:
                    continue
                codec, comm = cost.best_codec(br.out)
                comps.append(_Component(
                    kind="branch", nodes=list(br.nodes),
                    cuts=segment_cut_points(graph, br.nodes, r.fork),
                    edge_comm=comm, edge_codec=codec,
                    region=r, path=p))

    for c in comps:
        acc = 0.0
        cum_at = {}
        for n in c.nodes:
            acc += node_s[n]
            cum_at[n] = acc
        c.total = acc
        c.cum = [cum_at[x] for x in c.cuts]
        c.comm, c.codec_of = [], []
        for x in c.cuts:
            name, s = cost.best_codec(x)
            c.comm.append(s)
            c.codec_of.append(name)
    return comps


def _allocate(comps: list[_Component], num_nodes: int
              ) -> list[int] | None:
    """Stage counts per component minimizing the global bottleneck
    within the node budget: bisect over the union of per-component DP
    values; for a candidate bottleneck each component needs its
    SMALLEST stage count achieving it.  None when even one stage per
    component exceeds the budget."""
    if len(comps) > num_nodes:
        return None
    tables = []
    for c in comps:
        hi = min(c.max_stages, num_nodes - (len(comps) - 1))
        tables.append([c.partition(m)[1] for m in range(1, hi + 1)])
    cands = sorted({v for t in tables for v in t})

    def needs(limit: float) -> list[int] | None:
        out = []
        for t in tables:
            m = next((i + 1 for i, v in enumerate(t)
                      if v <= limit * (1 + _EPS) + _EPS), None)
            if m is None:
                return None
            out.append(m)
        return out if sum(out) <= num_nodes else None

    lo, hi = 0, len(cands) - 1
    best: list[int] | None = None
    while lo <= hi:
        mid = (lo + hi) // 2
        got = needs(cands[mid])
        if got is not None:
            best = got
            hi = mid - 1
        else:
            lo = mid + 1
    return best


def _assemble(graph: LayerGraph, cost: StageCostModel,
              node_s: dict[str, float], chosen: list[BranchRegion],
              comps: list[_Component], cuts_by_comp: list[list[int]],
              objective: str) -> DagPlan:
    """Materialize the stage-graph vertices for one topology candidate
    (component list + chosen cut indices per component) — shared by the
    DP solver and the brute-force oracle so both score identically."""
    by_fork = {r.fork: r for r in chosen}
    # group components back into spine order: trunk segments with their
    # regions' branch components attached
    plan_vertices: list[DagVertex] = []
    vid = 0

    def stage_slices(c: _Component, chosen_idx: list[int]):
        pos = {n: i for i, n in enumerate(c.nodes)}
        cut_pos = [pos[c.cuts[i]] for i in chosen_idx]
        bounds = [-1] + cut_pos + [len(c.nodes) - 1]
        out = []
        for k in range(len(cut_pos) + 1):
            lo, hi = bounds[k] + 1, bounds[k + 1] + 1
            out.append(c.nodes[lo:hi])
        return out

    def vertex_costs(c: _Component, chosen_idx: list[int]):
        bounds = [0.0] + [c.cum[i] for i in chosen_idx] + [c.total]
        comp_s = [bounds[k + 1] - bounds[k]
                  for k in range(len(chosen_idx) + 1)]
        comm_s = [c.comm[i] for i in chosen_idx] + [c.edge_comm]
        codecs = [c.codec_of[i] for i in chosen_idx] + [c.edge_codec]
        return comp_s, comm_s, codecs

    trunk_comps = [(i, c) for i, c in enumerate(comps)
                   if c.kind == "trunk"]
    branch_comps = {}
    for i, c in enumerate(comps):
        if c.kind == "branch":
            branch_comps.setdefault(id(c.region), {})[c.path] = (i, c)

    pending_join: BranchRegion | None = None
    for seg_no, (ci, c) in enumerate(trunk_comps):
        slices = stage_slices(c, cuts_by_comp[ci])
        comp_s, comm_s, codecs = vertex_costs(c, cuts_by_comp[ci])
        n_stages = len(slices)
        for k, sl in enumerate(slices):
            is_first = k == 0
            is_last = k == n_stages - 1
            join_of = pending_join if is_first else None
            if is_first and pending_join is not None:
                inputs = tuple(graph.nodes[pending_join.join].inputs)
                join_n = pending_join.width
                pending_join = None
            else:
                inputs = ((graph.input_name,) if vid == 0
                          else (plan_vertices[-1].output,))
                join_n = 0
            if is_first and join_of is not None:
                # seed order sanity: slice starts at the join node
                assert sl[0] == join_of.join
            fork_r = by_fork.get(sl[-1]) if is_last else None
            plan_vertices.append(DagVertex(
                vid=vid, nodes=tuple(sl), inputs=inputs,
                output=sl[-1], next=(),
                fan="broadcast" if fork_r is not None else "unicast",
                join=join_n if join_n >= 2 else 0,
                codec=codecs[k], compute_s=comp_s[k], comm_s=comm_s[k]))
            prev_vid = vid
            vid += 1
            if not is_last:
                plan_vertices[prev_vid].next = (vid,)
        if c.nodes[-1] in by_fork:
            r = by_fork[c.nodes[-1]]
            fork_vid = vid - 1
            # lay out each branch's sub-chain in path order; empty
            # branches wire the fork straight to the (future) join
            heads: list[int | None] = []
            per_branch = branch_comps.get(id(r), {})
            bvid = vid
            for p, br in enumerate(r.branches):
                if br.empty:
                    heads.append(None)
                    continue
                bi, bc = per_branch[p]
                b_slices = stage_slices(bc, cuts_by_comp[bi])
                b_comp, b_comm, b_codecs = vertex_costs(
                    bc, cuts_by_comp[bi])
                heads.append(bvid)
                for k, sl in enumerate(b_slices):
                    inputs = ((r.fork,) if k == 0
                              else (plan_vertices[-1].output,))
                    plan_vertices.append(DagVertex(
                        vid=bvid, nodes=tuple(sl), inputs=inputs,
                        output=sl[-1], next=(),
                        branch=p, codec=b_codecs[k],
                        compute_s=b_comp[k], comm_s=b_comm[k]))
                    if k > 0:
                        plan_vertices[bvid - 1].next = (bvid,)
                    bvid += 1
            join_vid = bvid
            vid = bvid
            # wire fork -> heads (empty branch -> join) and branch
            # tails -> join
            nxt = []
            for p, h in enumerate(heads):
                nxt.append(join_vid if h is None else h)
            plan_vertices[fork_vid].next = tuple(nxt)
            for p, h in enumerate(heads):
                if h is None:
                    continue
                tail = h
                while plan_vertices[tail].next:
                    tail = plan_vertices[tail].next[0]
                plan_vertices[tail].next = (join_vid,)
            pending_join = r

    plan = DagPlan(
        graph_name=graph.name, vertices=plan_vertices,
        objective=objective, cost=cost.describe(),
        parallel_regions=[{"fork": r.fork, "join": r.join,
                           "paths": r.width} for r in chosen])
    return plan


def _region_subsets(regions: list[BranchRegion], max_subsets: int):
    r = len(regions)
    if 2 ** r <= max_subsets:
        yield from itertools.product((False, True), repeat=r)
        return
    # too many regions to enumerate: free bits for the costliest ones
    # (by serialized branch work), the rest stay inline
    free = max(1, max_subsets.bit_length() - 1)
    order = sorted(range(r),
                   key=lambda i: -sum(len(b.nodes)
                                      for b in regions[i].branches))
    hot = set(order[:free])
    for bits in itertools.product((False, True), repeat=len(hot)):
        flags = [False] * r
        for i, b in zip(sorted(hot), bits):
            flags[i] = b
        yield tuple(flags)


def best_linear_plan(graph: LayerGraph, cost: StageCostModel,
                     num_nodes: int):
    """Best cuts-only chain plan within a node budget — the comparison
    baseline every DAG plan must beat (docs/PLANNER.md)."""
    from .solver import solve
    max_s = min(num_nodes, len(valid_cut_points(graph)) + 1)
    return min((solve(graph, s, cost) for s in range(1, max_s + 1)),
               key=lambda p: p.bottleneck_s)


def solve_dag(graph: LayerGraph, cost: StageCostModel, *,
              num_nodes: int, hop_tiers: dict[str, str] | None = None,
              max_subsets: int = 4096) -> DagPlan:
    """Best branch-parallel stage graph for a budget of ``num_nodes``
    processes (see module docstring).  Regions whose fork is the graph
    input stay inline — the dispatcher feeds exactly one entry stage.
    A graph with no separable regions (or a budget too tight to fan)
    degenerates to the linear chain plan, topology included."""
    if num_nodes < 1:
        raise ValueError("num_nodes must be >= 1")
    regions = [r for r in branch_regions(graph)
               if r.fork != graph.input_name]
    _validate_dag_tiers(graph, hop_tiers, regions)
    if hop_tiers is not None:
        # key namespace: every stage-graph cut plus the branch-output
        # boundaries (real deployable hops into a join; the wire-framed
        # check above already rejected non-tcp tiers on them)
        valid = list(dag_cut_points(graph)) + [
            b.out for r in regions for b in r.branches if not b.empty]
        cost = cost.with_hop_tiers(hop_tiers, valid_cuts=valid)
    node_s = {n: cost.node_seconds(n) for n in graph.topo_order}

    best: DagPlan | None = None
    best_key = None
    for flags in _region_subsets(regions, max_subsets):
        chosen = [r for r, f in zip(regions, flags) if f]
        min_nodes = (1 + len(chosen)
                     + sum(sum(1 for b in r.branches if not b.empty)
                           for r in chosen))
        if min_nodes > num_nodes:
            continue
        comps = _components_for(graph, cost, node_s, chosen)
        alloc = _allocate(comps, num_nodes)
        if alloc is None:
            continue
        cuts_by_comp = [c.partition(m)[0] for c, m in zip(comps, alloc)]
        plan = _assemble(graph, cost, node_s, chosen, comps,
                         cuts_by_comp, "critical_path")
        key = (round(plan.bottleneck_s, 12),
               round(plan.critical_path_s, 12), plan.num_nodes)
        if best_key is None or key < best_key:
            best, best_key = plan, key
    assert best is not None  # the empty subset with 1 node always fits
    return best


def brute_force_dag(graph: LayerGraph, cost: StageCostModel, *,
                    num_nodes: int) -> DagPlan:
    """Exhaustive region-subset x per-component cut enumeration (test
    oracle for :func:`solve_dag`; keep the graph under ~10 stage-graph
    cuts and the budget under ~6)."""
    regions = [r for r in branch_regions(graph)
               if r.fork != graph.input_name]
    node_s = {n: cost.node_seconds(n) for n in graph.topo_order}
    best: DagPlan | None = None
    best_key = None
    for flags in itertools.product((False, True), repeat=len(regions)):
        chosen = [r for r, f in zip(regions, flags) if f]
        comps = _components_for(graph, cost, node_s, chosen)
        if len(comps) > num_nodes:
            continue
        spare = num_nodes - len(comps)
        choice_sets = []
        for c in comps:
            opts = []
            for k in range(0, min(len(c.cuts), spare) + 1):
                opts.extend(list(x)
                            for x in itertools.combinations(
                                range(len(c.cuts)), k))
            choice_sets.append(opts)
        for combo in itertools.product(*choice_sets):
            if sum(len(x) + 1 for x in combo) > num_nodes:
                continue
            plan = _assemble(graph, cost, node_s, chosen, comps,
                             [list(x) for x in combo], "brute_force_dag")
            key = (round(plan.bottleneck_s, 12),
                   round(plan.critical_path_s, 12), plan.num_nodes)
            if best_key is None or key < best_key:
                best, best_key = plan, key
    assert best is not None
    return best
