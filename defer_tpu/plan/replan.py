"""Telemetry-driven replanning: correct the cost model with live metrics.

The planner's compute model is analytic (or one-shot measured) and will
be wrong in ways only a running deployment can reveal — XLA fusion
across a stage, host dispatch overhead, a slow host.  The telemetry PR
already publishes per-stage latency histograms; this module closes the
loop:

1. :func:`measured_stage_seconds` pulls per-stage seconds out of either
   a ``MetricsRegistry`` snapshot (``<prefix>.stage<k>.latency_s``
   summaries from ``SpmdPipeline.stage_latencies`` /
   ``PipelineMetrics.bind``) or a ``ChainDispatcher.stats`` reply list
   (each node's ``infer_latency_s`` summary).
2. :func:`replan` scales every node cost inside old stage ``k`` by
   ``measured_k / predicted_k`` (the stage is the granularity telemetry
   gives us), re-solves with the corrected model, and reports a plan
   diff — so the cost model is corrected by what the chain actually did
   instead of trusted blindly.

Corrections are multiplicative and per-stage: relative node weights
inside a stage keep the model's shape, while the stage total matches
reality.  Stages with no samples keep factor 1.0.
"""

from __future__ import annotations

import dataclasses
import re
import time
from typing import Sequence

from ..graph.ir import LayerGraph
from .cost import CodecSpec, StageCostModel
from .solver import (Plan, ReplicatedPlan, evaluate_cuts, solve,
                     solve_replicated)

_STAGE_KEY = re.compile(r"(?:^|\.)stage(\d+)\.latency_s$")


def _window_mean(now, base) -> float | None:
    """Delta-mean of a cumulative summary against a baseline snapshot:
    ``(sum - sum0) / (count - count0)``.  Percentiles cannot be
    subtracted; the exact sum/count fields can — the window-bounded
    form that scores the CURRENT regime instead of the lifetime fold
    (a serve chain's cold-start/compile samples otherwise skew the
    average forever)."""
    if not isinstance(base, dict) or not base.get("count"):
        return None
    n = int(now.get("count", 0)) - int(base.get("count", 0))
    if n <= 0:
        return None
    return (float(now.get("sum", 0.0))
            - float(base.get("sum", 0.0))) / n


def measured_stage_seconds(source, *, quantile: str = "p50",
                           scale: float = 1.0,
                           baseline=None) -> dict[int, float]:
    """stage index -> measured seconds, from telemetry.

    ``source`` is a registry snapshot dict (histogram summaries under
    ``...stage<k>.latency_s`` keys, seconds), a list of node ``stats``
    dicts (``{"stage": k, "infer_latency_s": {...}}``), or a direct
    ``{stage: seconds}`` mapping (e.g. a live
    ``ClusterView.stage_service_ms()`` converted to seconds — the
    full-service estimate, which unlike infer-only latency includes a
    stage's per-hop codec costs).
    ``quantile`` picks the summary field (p50 by default — the
    steady-state number; mean is skewed by compile outliers).  ``scale``
    converts units if the source was exported scaled.

    ``baseline`` is an EARLIER snapshot of the same shape: when given,
    each summary is reduced to its window-bounded delta-mean against
    the matching baseline summary (see :func:`_window_mean`) — the form
    replan/calibration use on long-running chains, where the lifetime
    histograms average cold-start samples in forever.  Summaries with
    no baseline match (or no new samples) keep the lifetime figure.

    Replicated stages report one ``stats`` row per replica; their
    per-frame service times are averaged into one per-stage figure (a
    replica's latency measures the UNDIVIDED stage cost — the division
    by R happens in the solver's objective, not in telemetry).
    """
    acc: dict[int, list[float]] = {}
    base_map: dict = {}
    if isinstance(baseline, dict):
        for key, summ in baseline.items():
            m = _STAGE_KEY.search(key)
            if m:
                base_map[int(m.group(1))] = summ
    elif baseline is not None:
        for row in baseline:
            if isinstance(row, dict) and row.get("stage") is not None:
                base_map[(int(row["stage"]), row.get("replica"))] = \
                    row.get("infer_latency_s")

    def take(stage: int, summ, base_key=None) -> None:
        if not isinstance(summ, dict) or not summ.get("count"):
            return
        win = _window_mean(summ, base_map.get(base_key)) \
            if base_key is not None else None
        v = win if win is not None else summ.get(quantile,
                                                 summ.get("mean"))
        if v is not None:
            acc.setdefault(int(stage), []).append(float(v) * scale)

    if isinstance(source, dict) and source and all(
            (isinstance(k, int) or (isinstance(k, str) and k.isdigit()))
            and isinstance(v, (int, float)) and not isinstance(v, bool)
            for k, v in source.items()):
        # direct {stage: seconds} mapping: pass through (scaled).  Keys
        # must LOOK like stage indices — an all-numeric registry
        # snapshot (counters/gauges only) must fall through to the
        # pattern search below and yield {}, not crash on int("a.b")
        return {int(k): float(v) * scale for k, v in source.items()}
    if isinstance(source, dict):
        for key, summ in source.items():
            m = _STAGE_KEY.search(key)
            if m:
                take(int(m.group(1)), summ, base_key=int(m.group(1)))
    else:  # ChainDispatcher.stats reply list (one row per replica)
        for row in source:
            if isinstance(row, dict) and row.get("stage") is not None:
                take(row["stage"], row.get("infer_latency_s"),
                     base_key=(int(row["stage"]), row.get("replica")))
    return {k: sum(vs) / len(vs) for k, vs in acc.items()}


@dataclasses.dataclass
class ReplanResult:
    old_plan: Plan
    #: the old cuts re-scored under the corrected model — the honest
    #: baseline the new plan's improvement is measured against
    old_plan_corrected: Plan
    new_plan: Plan
    #: per-old-stage measured/predicted factors applied to node costs
    corrections: dict[int, float]
    measured_stage_s: dict[int, float]

    @property
    def moved(self) -> bool:
        return self.new_plan.cuts != self.old_plan.cuts \
            or self.new_plan.codecs != self.old_plan.codecs \
            or getattr(self.new_plan, "replicas", None) \
            != getattr(self.old_plan, "replicas", None)

    @property
    def predicted_improvement(self) -> float:
        """corrected-old bottleneck / new bottleneck (>1 = replan wins)."""
        if self.new_plan.bottleneck_s <= 0:
            return 1.0
        return self.old_plan_corrected.bottleneck_s \
            / self.new_plan.bottleneck_s

    def to_json(self) -> dict:
        return {
            "moved": self.moved,
            "predicted_improvement": round(self.predicted_improvement, 4),
            "corrections": {k: round(v, 4)
                            for k, v in sorted(self.corrections.items())},
            "measured_stage_ms": {
                k: round(v * 1e3, 4)
                for k, v in sorted(self.measured_stage_s.items())},
            "old": self.old_plan.to_json(),
            "old_corrected": self.old_plan_corrected.to_json(),
            "new": self.new_plan.to_json(),
        }

    def apply(self, live: "LiveReplan", *,
              min_improvement: float = 1.0) -> dict | None:
        """Act on the suggestion: cut the live chain over to
        ``new_plan`` through ``live`` (quiesce -> redeploy -> resume,
        docs/ROBUSTNESS.md).  Returns the cutover receipt, or None when
        the suggestion moved nothing / predicts less than
        ``min_improvement`` — a suggestion that is not worth a cutover
        should cost nothing."""
        if not self.moved or self.predicted_improvement < min_improvement:
            return None
        return live.apply(self.new_plan)


class LiveReplan:
    """Zero-downtime mid-stream replan over persist-mode stage nodes.

    The replay/quiesce substrate's second consumer (the first is
    replica failover — docs/ROBUSTNESS.md): between stream segments,
    quiesce every stage at a stable sequence point, end the segment's
    data-plane connections (the dispatcher's result server and sequence
    counter survive — :meth:`ChainDispatcher.end_stream`), ship the
    re-cut stage artifacts over the SAME in-band deploy path that
    booted the chain, and resume streaming.  The nodes never restart,
    no port moves, and the output stream stays byte-identical to an
    undisturbed run because the cutover sits exactly on a segment
    boundary.

    Requires every node to run ``--persist`` (survive stream END until
    an explicit ``shutdown``) — the constructor cannot verify that, so
    a non-persist node surfaces as a connect failure on the segment
    after the first cutover.

    The cutover redeploys onto the SAME process set: ``new_plan.cuts``
    must produce ``len(node_addrs)`` stages (replica-count changes need
    a supervisor respawn, which is failover's mechanism, not this one).
    """

    def __init__(self, dispatcher, graph, params,
                 node_addrs: Sequence, *, batch: int = 1,
                 codecs: Sequence[str] | None = None,
                 quiesce_timeout_s: float = 30.0):
        self.dispatcher = dispatcher
        self.graph = graph
        self.params = params
        self.node_addrs = list(node_addrs)
        self.batch = batch
        self.codecs = list(codecs) if codecs else None
        self.quiesce_timeout_s = quiesce_timeout_s
        #: cutovers performed (the obs counter's pull twin)
        self.cutovers = 0

    def apply(self, new_plan, *, at_seq: int | None = None) -> dict:
        """One cutover: quiesce -> end segment -> in-band redeploy ->
        ready for the next ``stream`` segment.  Returns a receipt dict
        (per-stage quiesced counts, stage count, recovery time)."""
        from ..obs.events import emit as _emit
        from ..partition.partitioner import partition

        t0 = time.perf_counter()
        disp = self.dispatcher
        stages = partition(self.graph, list(new_plan.cuts))
        if len(stages) != len(self.node_addrs):
            raise ValueError(
                f"plan cuts produce {len(stages)} stages but the live "
                f"chain has {len(self.node_addrs)} nodes — a live "
                f"replan keeps the process set")
        processed = disp.quiesce(self.node_addrs, at_seq=at_seq,
                                 timeout_s=self.quiesce_timeout_s)
        disp.end_stream()
        # plan codecs are per CUT (N-1 interior hops); deploy wants one
        # OUTBOUND codec per stage — the exit stage's result hop rides
        # the dispatcher default
        codecs = self.codecs
        if getattr(new_plan, "codecs", None):
            codecs = list(new_plan.codecs) + [disp.codec]
        disp.deploy(stages, self.params, self.node_addrs,
                    batch=self.batch, codecs=codecs)
        self.cutovers += 1
        receipt = {"stages": len(stages),
                   "quiesced": processed,
                   "cuts": list(new_plan.cuts),
                   "cutover_ms": round(
                       (time.perf_counter() - t0) * 1e3, 3)}
        _emit("cutover", stages=len(stages), quiesced=processed)
        return receipt

    def shutdown(self) -> None:
        """Release the persist nodes: send each the ``shutdown``
        control command so their serve loops return."""
        self.dispatcher.shutdown_nodes(self.node_addrs)


def cost_model_from_plan(graph: LayerGraph, plan: Plan) -> StageCostModel:
    """A cost model whose per-stage compute totals reproduce the plan's
    own ``stage_compute_s`` (spread uniformly over each stage's nodes).

    The right default when replanning against a plan whose original
    model is gone — a monitor that loaded plan JSON, or ``run_chain``'s
    live straggler suggestion: per-stage correction factors
    (measured / predicted) only need the stage TOTALS, which this model
    matches exactly; the uniform spread inside a stage makes the
    re-solve approximate, which a suggestion is anyway."""
    order = graph.topo_order
    pos = {n: i for i, n in enumerate(order)}
    bounds = [0] + [pos[c] + 1 for c in plan.cuts] + [len(order)]
    node_costs: dict[str, float] = {}
    for k in range(len(bounds) - 1):
        names = order[bounds[k]:bounds[k + 1]]
        per = plan.stage_compute_s[k] / max(1, len(names))
        for n in names:
            node_costs[n] = per
    # adopt the plan's per-hop transport tiers: a replan seeded from
    # plan JSON keeps scoring the deployment's colocated hops on their
    # tier pseudo-codecs instead of re-modeling them as TCP
    tiers = {c: t for c, t in zip(plan.cuts,
                                  getattr(plan, "hop_tiers", None) or [])
             if t != "tcp"}
    # a CALIBRATED model's codec table (fitted throughputs, possibly
    # codec names the analytic defaults never heard of) travels in the
    # plan's cost_model dict too — restore it, or a replan seeded from
    # a calibrated plan silently reverts to guessed codec constants
    codec_doc = (plan.cost or {}).get("codecs")
    codecs = {n: CodecSpec(**c) for n, c in codec_doc.items()} \
        if codec_doc else None
    return StageCostModel(
        graph, node_costs=node_costs, hop_tiers=tiers or None,
        codecs=codecs,
        # comm terms scale with the frame batch (cut_bytes): restore
        # the plan's, or a batch-N plan's hops re-price at batch 1
        batch=int((plan.cost or {}).get("batch") or 1),
        link_bw_s=(plan.cost or {}).get("link_bw_s"),
        # the tier map's bandwidth half travels in the plan's cost_model
        # dict — without it a calibrated local_bw_s would silently reset
        # to the default in replans seeded from plan JSON (likewise the
        # ici interconnect and host-sync bandwidths)
        local_bw_s=(plan.cost or {}).get("local_bw_s"),
        ici_bw_s=(plan.cost or {}).get("ici_bw_s"),
        host_sync_bw_s=(plan.cost or {}).get("host_sync_bw_s"))


def corrected_cost_model(graph: LayerGraph, plan: Plan,
                         cost: StageCostModel,
                         measured: dict[int, float]) -> StageCostModel:
    """``cost`` with node seconds rescaled so each old stage's total
    matches its measured seconds (unmeasured stages keep factor 1)."""
    order = graph.topo_order
    pos = {n: i for i, n in enumerate(order)}
    bounds = [0] + [pos[c] + 1 for c in plan.cuts] + [len(order)]
    node_costs: dict[str, float] = {}
    for k in range(len(bounds) - 1):
        names = order[bounds[k]:bounds[k + 1]]
        predicted = cost.compute_seconds(names)
        factor = 1.0
        if k in measured and predicted > 0:
            factor = measured[k] / predicted
        for n in names:
            # node_seconds is already at the model's batch; node_costs
            # entries are consumed as-is, so no batch rescaling here
            node_costs[n] = cost.node_seconds(n) * factor
    return StageCostModel(
        graph, batch=cost.batch, gen=cost.gen,
        peak_flops_s=cost.peak_flops_s, hbm_bw_s=cost.hbm_bw_s,
        link_bw_s=cost.link_bw_s, codecs=cost.codecs,
        node_costs=node_costs,
        # tier-aware costs survive the correction: colocated hops stay
        # colocated in the re-solve
        hop_tiers=getattr(cost, "hop_tiers", None) or None,
        local_bw_s=getattr(cost, "local_bw_s", None),
        ici_bw_s=getattr(cost, "ici_bw_s", None),
        host_sync_bw_s=getattr(cost, "host_sync_bw_s", None))


def replan(graph: LayerGraph, plan: Plan, source,
           cost: StageCostModel | None = None, *,
           quantile: str = "p50") -> ReplanResult:
    """Re-solve ``plan`` with telemetry-corrected stage costs.

    ``source`` is a registry snapshot or node-stats list (see
    :func:`measured_stage_seconds`).  ``cost`` defaults to a fresh
    analytic model matching the plan's stage count assumptions — pass
    the model the plan was built with when available.

    A :class:`ReplicatedPlan` replans under the SAME node budget: the
    corrected old plan keeps its cuts and replica counts, the new plan
    re-runs :func:`solve_replicated` with ``num_nodes`` — so telemetry
    can move replicas to whichever stage measurement proved slow, not
    just move the cuts.
    """
    if cost is None:
        cost = StageCostModel(graph)
    measured = measured_stage_seconds(source, quantile=quantile)
    corrected = corrected_cost_model(graph, plan, cost, measured)
    order = graph.topo_order
    pos = {n: i for i, n in enumerate(order)}
    bounds = [0] + [pos[c] + 1 for c in plan.cuts] + [len(order)]
    corrections = {}
    for k in range(len(bounds) - 1):
        names = order[bounds[k]:bounds[k + 1]]
        pred = cost.compute_seconds(names)
        corrections[k] = (measured[k] / pred
                          if k in measured and pred > 0 else 1.0)
    if isinstance(plan, ReplicatedPlan):
        old_corrected = evaluate_cuts(graph, plan.cuts, corrected,
                                      objective=plan.objective,
                                      replicas=plan.replicas)
        new_plan = solve_replicated(graph, corrected,
                                    num_nodes=plan.num_nodes)
    else:
        old_corrected = evaluate_cuts(graph, plan.cuts, corrected,
                                      objective=plan.objective)
        new_plan = solve(graph, plan.num_stages, corrected)
    return ReplanResult(old_plan=plan, old_plan_corrected=old_corrected,
                        new_plan=new_plan, corrections=corrections,
                        measured_stage_s=measured)
