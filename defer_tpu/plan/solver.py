"""Exact bottleneck-minimizing partition planner over the valid-cut chain.

Pipeline throughput at steady state is ``1 / max_k max(compute_k,
comm_k)`` — the slowest of every stage's compute and every hop's
transport ("The TensorFlow Partitioning and Scheduling Problem: It's the
Critical Path!", PAPERS.md, makes the general form of this argument).
The greedy quantile heuristic in ``graph.analysis.auto_cut_points``
balances cumulative *compute* only; this module minimizes the true
bottleneck exactly:

* ``solve`` — O(C^2 * S) dynamic program over the C valid cuts:

      dp[s][i] = min over j < i of
                 max(dp[s-1][j], compute(j..i), comm(i))

  where ``compute(j..i)`` is the prefix-sum difference of per-node
  seconds and ``comm(i)`` is the *cheapest-codec* transport time at cut
  ``i`` (codec choice is separable: each hop's codec affects only that
  hop's term of the max, so the per-hop argmin is globally optimal).

* ``solve(method="bisect")`` — binary search over the O(C^2) candidate
  bottleneck values with a greedy O(C) feasibility check (place each cut
  as far right as the limit allows).  Same optimum, near-linear per
  probe; cross-checked against the DP in tests.

The final relay back to the dispatcher (SPMD wrap hop / chain result
hop) is cut-independent — the output tensor is fixed — so it is reported
on the plan but excluded from the objective.
"""

from __future__ import annotations

import dataclasses

from ..graph.analysis import valid_cut_points
from ..graph.ir import LayerGraph
from .cost import StageCostModel


@dataclasses.dataclass
class Plan:
    """A solved (or evaluated) pipeline partition with its predictions."""

    graph_name: str
    num_stages: int
    cuts: list[str]
    codecs: list[str]              #: per hop, len == len(cuts)
    stage_compute_s: list[float]   #: len == num_stages
    hop_comm_s: list[float]        #: len == len(cuts)
    bottleneck_s: float
    objective: str
    cost: dict                     #: StageCostModel.describe()
    #: per-hop transport tier (tcp|local|device, len == len(cuts)) —
    #: which hops the cost model scored on the colocated fast path
    hop_tiers: list[str] = dataclasses.field(default_factory=list)

    @property
    def stage_cost_s(self) -> list[float]:
        """Per-stage steady-state cost: max(compute_k, comm_k)."""
        return [max(c, self.hop_comm_s[k]) if k < len(self.hop_comm_s)
                else c for k, c in enumerate(self.stage_compute_s)]

    @property
    def bottleneck_stage(self) -> int:
        costs = self.stage_cost_s
        return costs.index(max(costs)) if costs else 0

    @property
    def bound_by(self) -> str:
        """"compute" or "comm" — which side of the max binds."""
        k = self.bottleneck_stage
        if k < len(self.hop_comm_s) and \
                self.hop_comm_s[k] > self.stage_compute_s[k]:
            return "comm"
        return "compute"

    def predicted_throughput_per_s(self, batch: int = 1) -> float:
        return batch / self.bottleneck_s if self.bottleneck_s > 0 else 0.0

    def to_json(self) -> dict:
        return {
            "graph": self.graph_name,
            "objective": self.objective,
            "num_stages": self.num_stages,
            "cuts": list(self.cuts),
            "hop_codecs": list(self.codecs),
            "hop_tiers": list(self.hop_tiers)
            or ["tcp"] * len(self.cuts),
            "stage_compute_ms": [round(s * 1e3, 6)
                                 for s in self.stage_compute_s],
            "hop_comm_ms": [round(s * 1e3, 6) for s in self.hop_comm_s],
            "stage_cost_ms": [round(s * 1e3, 6) for s in self.stage_cost_s],
            "bottleneck_ms": round(self.bottleneck_s * 1e3, 6),
            "bottleneck_stage": self.bottleneck_stage,
            "bound_by": self.bound_by,
            "cost_model": self.cost,
        }


def _tables(graph: LayerGraph, cost: StageCostModel):
    """(cuts, cum compute prefix at each cut, total compute, per-cut
    (comm seconds, codec)) shared by every solver path."""
    cuts = valid_cut_points(graph)
    order = graph.topo_order
    node_s = {n: cost.node_seconds(n) for n in order}
    acc = 0.0
    cum_at = {}
    for n in order:
        acc += node_s[n]
        cum_at[n] = acc
    total = acc
    cum = [cum_at[c] for c in cuts]
    comm = []
    for c in cuts:
        name, s = cost.best_codec(c)
        comm.append((s, name))
    return cuts, cum, total, comm


def _mk_plan(graph, cost, chosen_idx, cuts, cum, total, comm,
             objective: str) -> Plan:
    bounds = [0.0] + [cum[i] for i in chosen_idx] + [total]
    stage_compute = [bounds[k + 1] - bounds[k]
                     for k in range(len(chosen_idx) + 1)]
    hop_comm = [comm[i][0] for i in chosen_idx]
    codecs = [comm[i][1] for i in chosen_idx]
    bottleneck = max([max(c, hop_comm[k]) if k < len(hop_comm) else c
                      for k, c in enumerate(stage_compute)] or [0.0])
    return Plan(graph_name=graph.name, num_stages=len(chosen_idx) + 1,
                cuts=[cuts[i] for i in chosen_idx], codecs=codecs,
                stage_compute_s=stage_compute, hop_comm_s=hop_comm,
                bottleneck_s=bottleneck, objective=objective,
                cost=cost.describe(),
                hop_tiers=[cost.hop_tier(cuts[i]) for i in chosen_idx])


def evaluate_cuts(graph: LayerGraph, cut_points: list[str],
                  cost: StageCostModel, *,
                  objective: str = "explicit",
                  replicas: list[int] | None = None,
                  hop_tiers: dict[str, str] | None = None,
                  hop_codecs: list[str] | None = None) -> Plan:
    """Predictions for an *explicit* cut list under ``cost`` (cheapest
    codec per hop) — how quantile or hand-picked cuts score on the same
    model the solver optimizes.  ``replicas`` (one count per stage)
    scores a replicated configuration instead: per-stage compute divides
    by its count and each hop's codec is re-chosen for the fan-adjusted
    ``enc/r_up + wire + dec/r_down`` cost.  ``hop_tiers`` (cut ->
    tcp|local|device) scores colocated hops on their tier pseudo-codec
    (:meth:`StageCostModel.with_hop_tiers`).

    ``hop_codecs`` (one per cut) PINS each hop to a codec instead of
    the argmin — how an audit rescoring a DEPLOYED plan prices the
    codecs that actually run; names the model has no row for fall back
    to ``raw`` (:meth:`StageCostModel.comm_parts_deployed`)."""
    if hop_tiers is not None:
        cost = cost.with_hop_tiers(hop_tiers)
    cuts, cum, total, comm = _tables(graph, cost)
    pos = {c: i for i, c in enumerate(cuts)}
    missing = [c for c in cut_points if c not in pos]
    if missing:
        raise ValueError(f"not valid cut points: {missing}")
    chosen = [pos[c] for c in cut_points]
    if hop_codecs is not None:
        if len(hop_codecs) != len(cut_points):
            raise ValueError(f"{len(cut_points)} cuts but "
                             f"{len(hop_codecs)} hop codecs")
        if replicas is not None:
            raise ValueError("hop_codecs pin is not supported together "
                             "with replicas (replicated hops re-choose "
                             "their codec for the fan shape)")
        comm = list(comm)
        for i, codec in zip(chosen, hop_codecs):
            comm[i] = (sum(cost.comm_parts_deployed(cuts[i], codec)),
                       codec)
    if replicas is None:
        return _mk_plan(graph, cost, chosen, cuts, cum, total, comm,
                        objective)
    return _mk_replicated_plan(graph, cost, chosen, cuts, cum, total,
                               list(replicas), objective)


def solve(graph: LayerGraph, num_stages: int, cost: StageCostModel, *,
          method: str = "dp",
          hop_tiers: dict[str, str] | None = None) -> Plan:
    """Optimal bottleneck plan for exactly ``num_stages`` stages.

    ``hop_tiers`` (cut -> tcp|local|device) lets cut placement exploit
    colocation: a cut whose hop is declared local/device costs its tier
    pseudo-codec (near zero) instead of the cheapest wire codec, so the
    solver is free to place cuts at fat boundaries the deployment
    crosses for free (docs/PLANNER.md)."""
    if hop_tiers is not None:
        cost = cost.with_hop_tiers(hop_tiers)
    if num_stages < 1:
        raise ValueError("num_stages must be >= 1")
    cuts, cum, total, comm = _tables(graph, cost)
    C = len(cuts)
    if C < num_stages - 1:
        raise ValueError(
            f"graph {graph.name!r} has only {C} valid cut points; "
            f"cannot make {num_stages} stages")
    if num_stages == 1:
        return _mk_plan(graph, cost, [], cuts, cum, total, comm,
                        "bottleneck")
    if method == "bisect":
        chosen = _solve_bisect(cum, total, [c[0] for c in comm],
                               num_stages)
    elif method == "dp":
        chosen = _solve_dp(cum, total, [c[0] for c in comm], num_stages)
    else:
        raise ValueError(f"unknown method {method!r}")
    return _mk_plan(graph, cost, chosen, cuts, cum, total, comm,
                    "bottleneck")


def _solve_dp(cum: list[float], total: float, comm: list[float],
              S: int) -> list[int]:
    """O(C^2 * S) DP; returns the chosen cut indices (len S-1)."""
    C = len(cum)
    INF = float("inf")
    # dp[i]: cut i is the s-th cut; parent[s][i]: the (s-1)-th cut's index
    dp = [INF] * C
    parent: list[list[int]] = []
    for i in range(C):
        # the s=1 row; cut i must leave >= S-2 cuts after it
        if C - 1 - i >= S - 2:
            dp[i] = max(cum[i], comm[i])
    parent.append([-1] * C)
    for s in range(2, S):
        nxt = [INF] * C
        par = [-1] * C
        for i in range(s - 1, C):
            if C - 1 - i < S - 1 - s:
                continue  # not enough cuts left for the later stages
            best, arg = INF, -1
            for j in range(s - 2, i):
                if dp[j] == INF:
                    continue
                v = max(dp[j], cum[i] - cum[j], comm[i])
                if v < best:
                    best, arg = v, j
            nxt[i], par[i] = best, arg
        dp, parent = nxt, parent + [par]
    best, last = INF, -1
    for i in range(S - 2, C):
        if dp[i] == INF:
            continue
        v = max(dp[i], total - cum[i])
        if v < best:
            best, last = v, i
    if last < 0:
        raise ValueError("no feasible plan (internal)")
    chosen = [last]
    for s in range(S - 2, 0, -1):
        chosen.append(parent[s][chosen[-1]])
    return chosen[::-1]


def _greedy_feasible(cum: list[float], total: float, comm: list[float],
                     S: int, limit: float) -> list[int] | None:
    """Cut indices (exactly S-1) achieving bottleneck <= limit, or None.

    With per-cut comm eligibility, naive farthest-cut greedy can strand
    the later stages on ineligible cuts, so the check is structural:

    * eligible cuts ``E`` = comm <= limit; any solution's cuts are a
      subset of ``E``, so if cutting at ALL of ``E`` still leaves a
      segment > limit, no subset can fix it -> infeasible;
    * the classic farthest-eligible greedy gives the MINIMAL cut count
      ``m``; using all of ``E`` gives the maximal; and adding any unused
      eligible cut to a valid solution keeps it valid (splitting only
      shrinks segments), so every count in ``[m, len(E)]`` is achievable
      -> feasible iff ``m <= S-1 <= len(E)``, padding the greedy
      solution with unused eligible cuts up to exactly S-1.
    """
    eps = 1e-12 + limit * 1e-9  # float-sum slack: DP and greedy add in
    #   different orders, so exact equality at the optimum must pass
    E = [i for i in range(len(cum)) if comm[i] <= limit + eps]
    if len(E) < S - 1:
        return None
    prev = 0.0
    for i in E:  # the finest available partition must itself fit
        if cum[i] - prev > limit + eps:
            return None
        prev = cum[i]
    if total - prev > limit + eps:
        return None
    chosen: list[int] = []
    prev_cum = 0.0
    idx = 0
    while total - prev_cum > limit + eps:
        pick = -1
        while idx < len(E) and cum[E[idx]] - prev_cum <= limit + eps:
            pick = E[idx]
            idx += 1
        if pick < 0:
            return None  # unreachable after the gap check; belt+braces
        chosen.append(pick)
        prev_cum = cum[pick]
    if len(chosen) > S - 1:
        return None  # needs more stages than allowed
    if len(chosen) < S - 1:  # pad with unused eligible cuts
        used = set(chosen)
        for i in E:
            if len(chosen) == S - 1:
                break
            if i not in used:
                chosen.append(i)
        chosen.sort()
    return chosen


def _solve_bisect(cum: list[float], total: float, comm: list[float],
                  S: int) -> list[int]:
    """Binary search over candidate bottleneck values + greedy check."""
    cands = set(comm)
    pts = [0.0] + cum
    for i, ci in enumerate(cum):
        for p in pts[: i + 1]:
            cands.add(ci - p)
    cands.update(total - c for c in cum)
    cands.add(total)
    ordered = sorted(c for c in cands if c >= 0.0)
    lo, hi = 0, len(ordered) - 1
    best: list[int] | None = None
    while lo <= hi:
        mid = (lo + hi) // 2
        got = _greedy_feasible(cum, total, comm, S, ordered[mid])
        if got is not None:
            best = got
            hi = mid - 1
        else:
            lo = mid + 1
    if best is None:
        raise ValueError("no feasible plan (internal)")
    return best


def sweep_stages(graph: LayerGraph, cost: StageCostModel, *,
                 max_stages: int | None = None,
                 latency_target_s: float | None = None) -> dict:
    """Solve for every stage count 1..max and pick a recommendation.

    Without a target: the stage count minimizing the bottleneck (ties to
    the fewest chips).  With ``latency_target_s``: the FEWEST stages
    whose bottleneck meets the target (chips are the scarce resource),
    falling back to the overall best when nothing meets it.
    """
    C = len(valid_cut_points(graph))
    hi = C + 1 if max_stages is None else min(max_stages, C + 1)
    plans = [solve(graph, n, cost) for n in range(1, hi + 1)]
    pick = min(plans, key=lambda p: (p.bottleneck_s, p.num_stages))
    met = None
    if latency_target_s is not None:
        feasible = [p for p in plans if p.bottleneck_s <= latency_target_s]
        if feasible:
            pick = min(feasible, key=lambda p: p.num_stages)
            met = True
        else:
            met = False
    return {"plans": plans, "recommended": pick,
            "latency_target_s": latency_target_s, "target_met": met}


def brute_force(graph: LayerGraph, num_stages: int,
                cost: StageCostModel) -> Plan:
    """Exhaustive reference solver (test oracle; exponential — keep the
    graph under ~12 valid cuts)."""
    import itertools
    cuts, cum, total, comm = _tables(graph, cost)
    if len(cuts) < num_stages - 1:
        raise ValueError("not enough cuts")
    best_plan = None
    for combo in itertools.combinations(range(len(cuts)), num_stages - 1):
        p = _mk_plan(graph, cost, list(combo), cuts, cum, total, comm,
                     "brute_force")
        if best_plan is None or p.bottleneck_s < best_plan.bottleneck_s:
            best_plan = p
    assert best_plan is not None
    return best_plan


def plan_from_json(doc: dict) -> "Plan":
    """Rebuild a :class:`Plan` / :class:`ReplicatedPlan` from its
    ``to_json()`` dict (what ``defer_tpu plan --json`` prints) — so a
    saved plan can seed telemetry replanning or the live monitor's
    straggler detector without re-solving."""
    doc = doc.get("plan", doc)  # accept a whole `plan --json` document
    kw = dict(
        graph_name=doc.get("graph", ""),
        num_stages=int(doc["num_stages"]),
        cuts=list(doc.get("cuts", [])),
        codecs=list(doc.get("hop_codecs", [])),
        stage_compute_s=[v / 1e3 for v in doc["stage_compute_ms"]],
        hop_comm_s=[v / 1e3 for v in doc.get("hop_comm_ms", [])],
        bottleneck_s=float(doc["bottleneck_ms"]) / 1e3,
        objective=doc.get("objective", "explicit"),
        cost=doc.get("cost_model", {}),
        hop_tiers=list(doc.get("hop_tiers", [])))
    if doc.get("replicas"):
        return ReplicatedPlan(**kw, replicas=list(doc["replicas"]),
                              num_nodes=int(doc.get("num_nodes", 0)))
    return Plan(**kw)


# -- hybrid pipeline/data-parallel: cuts + per-stage replica counts ----------


@dataclasses.dataclass
class ReplicatedPlan(Plan):
    """A plan whose stages may run as R data-parallel replicas.

    ``stage_compute_s`` stays the RAW (unreplicated) per-stage compute;
    ``hop_comm_s`` holds the fan-adjusted effective hop seconds
    (``enc/r_up + wire + dec/r_down`` at the chosen codec).  The
    effective stage cost divides compute by the stage's replica count —
    the runtime analogue being R replica processes each serving every
    R-th microbatch (docs/PLANNER.md).
    """

    replicas: list[int] = dataclasses.field(default_factory=list)
    num_nodes: int = 0

    @property
    def stage_cost_s(self) -> list[float]:
        eff = [c / max(r, 1)
               for c, r in zip(self.stage_compute_s, self.replicas)]
        return [max(c, self.hop_comm_s[k]) if k < len(self.hop_comm_s)
                else c for k, c in enumerate(eff)]

    @property
    def bound_by(self) -> str:
        k = self.bottleneck_stage
        eff = self.stage_compute_s[k] / max(self.replicas[k], 1)
        if k < len(self.hop_comm_s) and self.hop_comm_s[k] > eff:
            return "comm"
        return "compute"

    def to_json(self) -> dict:
        d = super().to_json()
        d["replicas"] = list(self.replicas)
        d["num_nodes"] = self.num_nodes
        d["stage_effective_ms"] = [
            round(c / max(r, 1) * 1e3, 6)
            for c, r in zip(self.stage_compute_s, self.replicas)]
        return d


def _mk_replicated_plan(graph, cost, chosen_idx, cuts, cum, total,
                        replicas: list[int], objective: str
                        ) -> ReplicatedPlan:
    if len(replicas) != len(chosen_idx) + 1:
        raise ValueError(
            f"{len(chosen_idx) + 1} stages but {len(replicas)} replica "
            f"counts")
    if any(r < 1 for r in replicas):
        raise ValueError(f"replica counts must be >= 1: {replicas}")
    for k in range(len(replicas) - 1):
        if replicas[k] > 1 and replicas[k + 1] > 1:
            raise ValueError(
                f"stages {k} and {k + 1} are both replicated; adjacent "
                f"replication is not supported (a replica cannot restore "
                f"another fan-out's order)")
    bounds = [0.0] + [cum[i] for i in chosen_idx] + [total]
    stage_compute = [bounds[k + 1] - bounds[k]
                     for k in range(len(chosen_idx) + 1)]
    hop_comm, codecs = [], []
    for k, i in enumerate(chosen_idx):
        name, s = cost.best_codec_replicated(cuts[i], replicas[k],
                                             replicas[k + 1])
        codecs.append(name)
        hop_comm.append(s)
    eff = [c / r for c, r in zip(stage_compute, replicas)]
    bottleneck = max([max(c, hop_comm[k]) if k < len(hop_comm) else c
                      for k, c in enumerate(eff)] or [0.0])
    # a tier only holds when neither side fans (runtime constraint —
    # see StageCostModel.best_codec_replicated); report what was scored
    tiers = [cost.hop_tier(cuts[i])
             if replicas[k] == 1 and replicas[k + 1] == 1 else "tcp"
             for k, i in enumerate(chosen_idx)]
    return ReplicatedPlan(
        graph_name=graph.name, num_stages=len(chosen_idx) + 1,
        cuts=[cuts[i] for i in chosen_idx], codecs=codecs,
        stage_compute_s=stage_compute, hop_comm_s=hop_comm,
        bottleneck_s=bottleneck, objective=objective,
        cost=cost.describe(), replicas=list(replicas),
        num_nodes=sum(replicas), hop_tiers=tiers)


def solve_replicated(graph: LayerGraph, cost: StageCostModel, *,
                     num_nodes: int,
                     hop_tiers: dict[str, str] | None = None
                     ) -> ReplicatedPlan:
    """Jointly optimal cuts AND per-stage replica counts for a budget of
    ``num_nodes`` processes, minimizing::

        max_k max(compute_k / r_k,
                  min_codec enc_k/r_k + wire_k + dec_k/r_{k+1})

    — the steady-state period of the hybrid pipeline/data-parallel
    chain.  Replicating a stage divides its compute (and its share of
    the adjoining hops' codec work) by R at the price of R-1 extra
    nodes somewhere else; when no single fat stage dominates, the DP
    simply returns more stages instead.  Adjacent stages cannot both be
    replicated (runtime constraint: a replica cannot restore another
    fan-out's sequence order).

    O(C² · N³) dynamic program over (last cut, nodes used, last stage's
    replica count); cross-checked against
    :func:`brute_force_replicated` in the property tests.

    ``hop_tiers`` (cut -> tcp|local|device): colocated hops cost their
    tier pseudo-codec whenever neither side is replicated (fan paths
    always ride tcp), so the joint DP trades replicas against fused or
    same-process boundaries on one objective.
    """
    if hop_tiers is not None:
        cost = cost.with_hop_tiers(hop_tiers)
    if num_nodes < 1:
        raise ValueError("num_nodes must be >= 1")
    N = num_nodes
    cuts, cum, total, _ = _tables(graph, cost)
    C = len(cuts)
    INF = float("inf")

    # hop_tab[i][ru][rd]: cheapest effective hop seconds at cut i for
    # upstream/downstream replica counts (codec argmin re-run per pair)
    hop_tab = [[[cost.best_codec_replicated(cuts[i], ru, rd)[1]
                 for rd in range(N + 1)] for ru in range(N + 1)]
               for i in range(C)]

    # dp[i][b][r]: best achievable max-so-far when the last completed
    # stage ends at cut i, b nodes are spent, and that stage runs r
    # replicas (the hop at cut i is NOT yet charged — it needs the next
    # stage's count)
    dp = [[[INF] * (N + 1) for _ in range(N + 1)] for _ in range(C)]
    par: dict[tuple[int, int, int], tuple[int, int, int] | None] = {}
    for i in range(C):
        for r in range(1, N):  # >= 1 node must remain for later stages
            dp[i][r][r] = cum[i] / r
            par[(i, r, r)] = None
    for b in range(1, N):
        for i in range(C):
            row = dp[i][b]
            for r in range(1, b + 1):
                v = row[r]
                if v == INF:
                    continue
                for i2 in range(i + 1, C):
                    seg = cum[i2] - cum[i]
                    for r2 in range(1, N - b):
                        if r > 1 and r2 > 1:
                            continue  # adjacent replication forbidden
                        val = max(v, hop_tab[i][r][r2], seg / r2)
                        if val < dp[i2][b + r2][r2]:
                            dp[i2][b + r2][r2] = val
                            par[(i2, b + r2, r2)] = (i, b, r)

    best_val, best_state, best_r_last = INF, None, 1
    for r in range(1, N + 1):  # single stage: no cuts, r-way replicas
        if total / r < best_val:
            best_val, best_state, best_r_last = total / r, None, r
    for i in range(C):
        for b in range(1, N):
            for r in range(1, b + 1):
                v = dp[i][b][r]
                if v == INF:
                    continue
                tail = total - cum[i]
                for r2 in range(1, N - b + 1):
                    if r > 1 and r2 > 1:
                        continue
                    val = max(v, hop_tab[i][r][r2], tail / r2)
                    if val < best_val:
                        best_val = val
                        best_state = (i, b, r)
                        best_r_last = r2

    chosen: list[int] = []
    replicas: list[int] = [best_r_last]
    state = best_state
    while state is not None:
        i, b, r = state
        chosen.append(i)
        replicas.append(r)
        state = par[(i, b, r)]
    chosen.reverse()
    replicas.reverse()
    return _mk_replicated_plan(graph, cost, chosen, cuts, cum, total,
                               replicas, "bottleneck_replicated")


def brute_force_replicated(graph: LayerGraph, cost: StageCostModel, *,
                           num_nodes: int) -> ReplicatedPlan:
    """Exhaustive cuts x replica-count enumeration (test oracle for
    :func:`solve_replicated`; keep the graph under ~8 valid cuts and
    the budget under ~6)."""
    import itertools
    cuts, cum, total, _ = _tables(graph, cost)
    N = num_nodes
    best = None
    for S in range(1, N + 1):
        if S - 1 > len(cuts):
            break
        for combo in itertools.combinations(range(len(cuts)), S - 1):
            for reps in itertools.product(range(1, N + 1), repeat=S):
                if sum(reps) > N:
                    continue
                if any(reps[k] > 1 and reps[k + 1] > 1
                       for k in range(S - 1)):
                    continue
                p = _mk_replicated_plan(graph, cost, list(combo), cuts,
                                        cum, total, list(reps),
                                        "brute_force_replicated")
                if best is None or p.bottleneck_s < best.bottleneck_s:
                    best = p
    assert best is not None
    return best


def sweep_nodes(graph: LayerGraph, cost: StageCostModel, *,
                max_nodes: int,
                latency_target_s: float | None = None) -> dict:
    """:func:`solve_replicated` for every node budget 1..max and pick a
    recommendation — the replication-aware analogue of
    :func:`sweep_stages`.  Without a target: the budget minimizing the
    bottleneck (ties to the fewest nodes).  With ``latency_target_s``:
    the FEWEST nodes whose bottleneck meets the target, falling back to
    the overall best when nothing does."""
    plans = [solve_replicated(graph, cost, num_nodes=n)
             for n in range(1, max_nodes + 1)]
    pick = min(plans, key=lambda p: (p.bottleneck_s, p.num_nodes))
    met = None
    if latency_target_s is not None:
        feasible = [p for p in plans if p.bottleneck_s <= latency_target_s]
        if feasible:
            pick = min(feasible, key=lambda p: p.num_nodes)
            met = True
        else:
            met = False
    return {"plans": plans, "recommended": pick,
            "latency_target_s": latency_target_s, "target_met": met}
