from .dispatcher import Defer, DeferConfig, DeferHandle, END_OF_STREAM
from .mpmd import MpmdPipeline
from .spmd import SpmdPipeline
