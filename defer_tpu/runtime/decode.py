"""Pipelined autoregressive decoding with per-stage KV caches.

The inference engine (:mod:`defer_tpu.runtime.spmd`) streams independent
inputs through the stage ring; generation is harder — token t+1 of a
sequence cannot enter stage 0 until token t has left the last stage.  A
single sequence would therefore keep only one of N stages busy.  The classic
fix, implemented here: interleave N independent *groups* of sequences
round-robin, so at every step stage k serves group ``(t - k) mod N`` — the
ring is full and every device computes every step, DEFER's "all stages busy
on different in-flight inputs" (SURVEY.md §0) transposed to token time.

TPU-native design, one SPMD program:

  * Weights: each device materializes only its stage's parameters from a
    stage-sharded flat buffer (same scheme as ``SpmdPipeline``), stored in
    the compute dtype.
  * KV caches: a per-device resident array
    ``[Lmax, N+1, mb, nh, max_len+1, hd]`` (local blocks x groups,
    head-major so attention needs no per-step cache transpose) in compute
    dtype; position row ``max_len`` is a scratch slot that warmup bubbles
    write into, and group slot ``N`` absorbs prefill bubbles — so no
    masked read-modify-write of the cache is ever needed.
  * The ring carry is one ``[mb, d]`` float32 buffer per device: stage
    activations in flight, and — on the wrap link from the last stage back
    to stage 0 (the reference's node->dispatcher link,
    src/dispatcher.py:51-55) — the greedily sampled token ids encoded in
    column 0 (f32 is exact for ids < 2^24).
  * ``lax.scan`` over decode steps fuses the token loop into chunked XLA
    dispatches (``token_chunk`` tokens per group per dispatch, whole
    generation in ONE dispatch by default); prompt teacher-forcing happens
    inside the scan (stage 0 substitutes the known prompt token while
    ``pos < prompt_len``), and the ring carry + caches flow between
    dispatches as donated device-resident shards — zero host round trips
    except the optional EOS check.
  * Sampling: greedy argmax, or temperature softmax sampling with optional
    top-k, keyed by ``fold_in(seed, step)`` so results are independent of
    the chunking.

Scope: stage-axis-only mesh, the ``gpt()`` node-name contract
(``embeddings`` / ``block_i`` / ``final_ln`` / ``lm_head`` —
models/gpt.py).  Prompts are processed either at decode rate (teacher
forcing inside the scan, the default) or by the fused full-sequence
pipelined prefill (``generate(..., prefill=True)``): each group's whole
prompt crosses each stage in one causal-attention step and bulk-seeds the
caches, dropping prompt cost from ``plen * N`` ring steps to ``2N - 1``.
"""

from __future__ import annotations

import time
from typing import Any

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..graph.ir import LayerGraph
from ..models.gpt import CausalTransformerBlock, GptEmbedding
from ..obs import REGISTRY, tracer
from ..parallel.mesh import STAGE_AXIS, pipeline_mesh
from ..utils.compat import shard_map
from ..utils.xla_opts import ring_jit_kwargs
from . import flatbuf


def _sample_ids(logits, temp, top_k, step_key):
    """Temperature softmax sampling with optional top-k truncation.

    The single definition shared by the decode and prefill branches — both
    must draw from the identical distribution."""
    lg = logits / jnp.maximum(temp, 1e-6)
    if top_k is not None:
        kth = lax.top_k(lg, top_k)[0][:, -1:]
        lg = jnp.where(lg >= kth, lg, -jnp.inf)
    return jax.random.categorical(step_key, lg, axis=-1)


def _split_blocks(num_blocks: int, num_stages: int) -> list[list[int]]:
    """Contiguous, balanced block assignment (stage i gets ~L/N blocks)."""
    bounds = [round(num_blocks * s / num_stages)
              for s in range(num_stages + 1)]
    out = [list(range(bounds[s], bounds[s + 1])) for s in range(num_stages)]
    if any(not b for b in out):
        raise ValueError(
            f"{num_blocks} blocks cannot fill {num_stages} stages")
    return out


class PipelinedDecoder:
    """Greedy autoregressive generation over a ``stage``-axis mesh.

    Usage::

        graph = gpt_tiny()
        dec = PipelinedDecoder(graph, graph.init(key), num_stages=4,
                               microbatch=2, max_len=32)
        tokens = dec.generate(prompt_ids, max_new_tokens=16)

    ``prompt_ids`` is [B, prompt_len] with B <= num_stages * microbatch;
    returns [B, prompt_len + max_new_tokens].
    """

    def __init__(
        self,
        graph: LayerGraph,
        params: dict[str, Any],
        *,
        num_stages: int,
        max_len: int | None = None,
        mesh: Mesh | None = None,
        microbatch: int = 1,
        compute_dtype=None,
        kv_cache: str = "buffer",
        weight_dtype: str | None = None,
        beam_width: int = 1,
    ):
        self.graph = graph
        self.num_stages = n = num_stages
        self.mesh = mesh if mesh is not None else pipeline_mesh(n)
        if self.mesh.shape[STAGE_AXIS] != n:
            raise ValueError(
                f"mesh stage axis {self.mesh.shape[STAGE_AXIS]} != {n}")
        self.microbatch = mb = microbatch
        self.compute_dtype = jnp.dtype(compute_dtype) if compute_dtype \
            else jnp.dtype(jnp.float32)
        if kv_cache not in ("buffer", "int8"):
            raise ValueError(
                f"kv_cache must be 'buffer' or 'int8', got {kv_cache!r}")
        self.kv_cache = kv_cache
        if weight_dtype not in (None, "int8"):
            raise ValueError(
                f"weight_dtype must be None or 'int8', got {weight_dtype!r}")
        #: W8A16: weights live int8 in HBM with channel-wise (last-axis)
        #: f32 scales, dequantized inside each stage branch.  Decode is
        #: HBM-bandwidth-bound (every step streams all weights), so int8
        #: halves the dominant traffic vs bf16.  1-D leaves (LN scales,
        #: biases) get per-element scales — exactly invertible.
        self.weight_quant = weight_dtype == "int8"
        if beam_width < 1 or mb % beam_width:
            raise ValueError(
                f"beam_width={beam_width} must be >= 1 and divide "
                f"microbatch={mb} (each group's rows hold "
                "microbatch/beam_width sequences x beam_width beams)")
        self.beam_width = beam_width

        nodes = graph.nodes
        for req in ("embeddings", "final_ln", "lm_head"):
            if req not in nodes:
                raise ValueError(
                    f"decoder graphs must follow the gpt() node contract; "
                    f"missing {req!r} (models/gpt.py)")
        self.embed_op: GptEmbedding = nodes["embeddings"].op
        if max_len is None:
            max_len = self.embed_op.max_len  # the positional table's reach
        self.max_len = max_len
        if max_len > self.embed_op.max_len:
            raise ValueError(
                f"max_len {max_len} exceeds the model's positional table "
                f"({self.embed_op.max_len})")
        block_names = [nm for nm in graph.topo_order
                       if nm.startswith("block_")]
        self.block_names = block_names
        for nm in block_names:
            if not isinstance(nodes[nm].op, CausalTransformerBlock):
                raise TypeError(f"{nm} is not a CausalTransformerBlock")
        self.d_model = nodes[block_names[0]].out_spec.shape[-1]
        self.num_heads = nodes[block_names[0]].op.num_heads
        self.num_kv_heads = nodes[block_names[0]].op.kv_heads
        self.head_dim = self.d_model // self.num_heads
        self.vocab = nodes["lm_head"].out_spec.shape[-1]
        for nm in block_names:
            op = nodes[nm].op
            if (op.num_heads, op.kv_heads) != (self.num_heads,
                                               self.num_kv_heads):
                raise ValueError(
                    f"{nm} has heads ({op.num_heads}, kv {op.kv_heads}) "
                    f"!= block_0's ({self.num_heads}, "
                    f"{self.num_kv_heads}); the homogeneous cache needs "
                    "one head geometry")

        assign = _split_blocks(len(block_names), n)
        self.stage_blocks = [[block_names[i] for i in idxs]
                             for idxs in assign]
        self.l_max = max(len(b) for b in self.stage_blocks)

        # --- stage-sharded flat weight buffer (scheme of runtime/spmd.py)
        stage_param_names: list[list[str]] = []
        for s in range(n):
            names = list(self.stage_blocks[s])
            if s == 0:
                names.insert(0, "embeddings")
            if s == n - 1:
                names += ["final_ln", "lm_head"]
            stage_param_names.append(names)
        self._stage_param_names = stage_param_names

        # weights live in the compute dtype (the runtime/spmd.py recipe):
        # bf16 deployments read 2 bytes/param from HBM per decode step with
        # no per-step downcast materialization
        wdt = np.dtype(jnp.bfloat16) if self.compute_dtype == jnp.bfloat16 \
            else np.float32
        self._wdt = wdt
        self._wmeta, self._wtreedef = [], []
        self._smeta: list[list[tuple[int, int]]] = []  # per-leaf scale slots
        self._w = jax.device_put(
            self._pack_wbuf(params, init=True),
            NamedSharding(self.mesh, P(STAGE_AXIS, None)))
        #: shard_map spec for the weight argument (pytree under W8A16)
        self._wspec_tree = jax.tree.map(lambda _: P(STAGE_AXIS, None),
                                        self._w)

        # group axis is n+1: slot n is the scratch group that pipelined
        # prefill's warmup/drain bubbles write into (the group-axis twin of
        # the max_len scratch row).  Head-major position axis per the
        # CausalTransformerBlock.decode cache contract; under GQA the head
        # axis is the (smaller) KV head count.
        self._cache_shape = (self.l_max, n + 1, mb, self.num_kv_heads,
                             max_len + 1, self.head_dim)
        #: per-row f32 scales for the int8 cache (one per head x position)
        self._scale_shape = (self.l_max, n + 1, mb, self.num_kv_heads,
                             max_len + 1)
        #: ring-buffer width: beam mode adds one column carrying each
        #: row's parent-beam index around the ring alongside the token id
        self._ring_width = self.d_model + (1 if beam_width > 1 else 0)
        #: compiled decode programs keyed by (chunk_steps, sample, top_k) —
        #: repeat ``generate`` calls of a matching shape are dispatch-only
        self._decode_fns: dict[tuple, Any] = {}
        #: compiled prefill programs keyed by (prompt_len, sample, top_k)
        self._prefill_fns: dict[tuple, Any] = {}
        self._init_fn = None  # cached jitted state initializer

    # ------------------------------------------------------------------

    def _pack_wbuf(self, params, *, init: bool = False) -> np.ndarray:
        """Pack ``params`` into the [N, Pmax] flat weight buffer; with
        ``init=False`` (reweight) the new leaves must match the deployed
        treedef/shapes/dtypes exactly (the compiled programs unflatten
        with the init-recorded layout)."""
        wdt = self._wdt
        flats, qflats, sflats = [], [], []
        for s, names in enumerate(self._stage_param_names):
            sub = {nm: params[nm] for nm in names}
            leaves, treedef = jax.tree.flatten(sub)
            # meta records PRE-cast shapes/dtypes so reweight validation
            # catches dtype drift before the blind wire-dtype cast
            if init:
                self._wmeta.append(flatbuf.leaf_meta(leaves))
                self._wtreedef.append(treedef)
            else:
                flatbuf.check_layout(leaves, treedef, self._wmeta[s],
                                     self._wtreedef[s],
                                     f"reweight: stage {s}")
            if not self.weight_quant:
                flats.append(flatbuf.pack_leaves(
                    [np.asarray(l).astype(wdt) for l in leaves], wdt))
                continue
            # W8A16: shared layout (flatbuf.quantize_leaves) — int8 values
            # at leaf_meta's element offsets + a parallel f32 scale row
            q_row, s_row, smeta = flatbuf.quantize_leaves(leaves)
            if init:
                self._smeta.append(smeta)
            qflats.append(q_row)
            sflats.append(s_row)
        if not self.weight_quant:
            return flatbuf.stack_rows(flats, wdt)
        return {"q": flatbuf.stack_rows(qflats, np.dtype(np.int8)),
                "s": flatbuf.stack_rows(sflats, np.dtype(np.float32))}

    def reweight(self, params) -> None:
        """Install fresh weights — no recompile, caches untouched.

        The decode analogue of ``SpmdPipeline.reweight``: compiled decode
        and prefill programs read the flat buffer as an argument, so a
        buffer swap redeploys (e.g. after further finetuning) without
        invalidating ``_decode_fns``/``_prefill_fns``.  Call between
        ``generate`` rounds — an in-flight generation keeps the weights
        it started with only up to its current dispatch boundary.
        """
        self._w = jax.device_put(
            self._pack_wbuf(params, init=False),
            NamedSharding(self.mesh, P(STAGE_AXIS, None)))

    def _stage_params(self, s: int, w_local):
        if not self.weight_quant:
            return flatbuf.unpack_leaves(w_local, self._wmeta[s],
                                         self._wtreedef[s])
        return flatbuf.unpack_quant_leaves(
            w_local["q"], w_local["s"], self._wmeta[s], self._smeta[s],
            self._wtreedef[s], self.compute_dtype)

    def _slice_lg(self, arr, l, g):
        """[Lmax, N+1, ...] cache entry -> the (block l, group g) item."""
        return lax.dynamic_slice(
            arr, (l, g) + (0,) * (arr.ndim - 2),
            (1, 1) + arr.shape[2:])[0, 0]

    def _write_lg(self, arr, item, l, g):
        return lax.dynamic_update_slice(
            arr, item[None, None], (l, g) + (0,) * (arr.ndim - 2))

    def _make_branch(self, s: int, sample: bool, top_k: int | None):
        """Stage ``s``'s step: consume the ring buffer, update caches.

        Uniform signature for ``lax.switch``:
        ``(w_local, a, caches, prompt, g, pos, plen, t, seed, temp,
        first_ids, first_pos) -> (a_out, caches)``.
        """
        n = self.num_stages
        nodes = self.graph.nodes
        cd = self.compute_dtype
        is_first, is_last = s == 0, s == n - 1
        block_ops = [nodes[nm].op for nm in self.stage_blocks[s]]
        embed_op = self.embed_op
        int8 = self.kv_cache == "int8"
        beam = self.beam_width
        mb = self.microbatch

        def branch(w_local, a, caches, prompt, g, pos, plen, t, seed, temp,
                   first_ids, first_pos):
            p = self._stage_params(s, w_local)
            # bubble steps (pos < 0 during warmup skew, or pos >= max_len
            # on chunk-overshoot steps past the requested generation) write
            # the cache scratch row and attend over nothing real; their
            # outputs are never read (host drops them by schedule index)
            valid = jnp.logical_and(pos >= 0, pos < self.max_len)
            safe_pos = jnp.clip(pos, 0, self.max_len - 1)
            write_pos = jnp.where(valid, safe_pos, self.max_len)

            if beam > 1:
                # re-parent this group's cache rows before appending the
                # incoming token: its activation was computed from the
                # CHOSEN beam's token, so history rows must match.  The
                # parent indices ride the ring in the extra column.  Only
                # beam-expansion arrivals (pos >= plen, non-bubble) carry
                # real parents — the cond skips the full-cache gather on
                # forced prompt steps and bubbles entirely.
                parents = jnp.clip(
                    jnp.round(a[:, self.d_model]).astype(jnp.int32),
                    0, mb - 1)
                applies = jnp.logical_and(valid, safe_pos >= plen)

                def reparent_all(cs):
                    def reparent(ent):
                        # [Lmax, n+1, mb, ...] -> rows of group g gathered
                        grp = lax.dynamic_slice(
                            ent, (0, g) + (0,) * (ent.ndim - 2),
                            (ent.shape[0], 1) + ent.shape[2:])
                        grp = jnp.take(grp, parents, axis=2)
                        return lax.dynamic_update_slice(
                            ent, grp, (0, g) + (0,) * (ent.ndim - 2))

                    return {nm: (reparent(c) if nm != "beam_cum" else c)
                            for nm, c in cs.items()}

                caches = lax.cond(applies, reparent_all,
                                  lambda cs: cs, caches)

            if is_first:
                recv_ids = jnp.round(a[:, 0]).astype(jnp.int32)
                prompt_ids = lax.dynamic_slice(
                    prompt, (g, 0, jnp.minimum(safe_pos, prompt.shape[2] - 1)),
                    (1, self.microbatch, 1))[0, :, 0]
                ids = jnp.where(safe_pos < plen, prompt_ids, recv_ids)
                # after a fused prefill the first generated token comes from
                # the prefill program, not the ring (first_pos = -1 disables)
                fi = lax.dynamic_slice(first_ids, (g, 0),
                                       (1, self.microbatch))[0]
                ids = jnp.where(safe_pos == first_pos, fi, ids)
                x = embed_op.embed_at(p["embeddings"], ids, safe_pos)
                x = x.astype(cd)
            else:
                x = a[:, : self.d_model].astype(cd)

            for l, (nm, op) in enumerate(zip(self.stage_blocks[s],
                                             block_ops)):
                k_l = self._slice_lg(caches["k"], l, g)
                v_l = self._slice_lg(caches["v"], l, g)
                if int8:
                    ks_l = self._slice_lg(caches["ks"], l, g)
                    vs_l = self._slice_lg(caches["vs"], l, g)
                    x, k_l, v_l, ks_l, vs_l = op.decode(
                        p[nm], x, k_l, v_l, write_pos, ks_l, vs_l)
                    caches = dict(
                        caches,
                        ks=self._write_lg(caches["ks"], ks_l, l, g),
                        vs=self._write_lg(caches["vs"], vs_l, l, g))
                else:
                    x, k_l, v_l = op.decode(p[nm], x, k_l, v_l, write_pos)
                caches = dict(caches,
                              k=self._write_lg(caches["k"], k_l, l, g),
                              v=self._write_lg(caches["v"], v_l, l, g))

            if is_last:
                h = nodes["final_ln"].op.apply(p["final_ln"], x)
                logits = nodes["lm_head"].op.apply(
                    p["lm_head"], h).astype(jnp.float32)
                a_out = jnp.zeros((mb, self._ring_width), jnp.float32)
                if beam > 1:
                    # beam expansion: per sequence, the best `beam` of
                    # beam*V continuations by cumulative log-probability
                    nseq = mb // beam
                    vocab = logits.shape[-1]
                    logp = jax.nn.log_softmax(logits, axis=-1)
                    cum = lax.dynamic_slice(caches["beam_cum"], (g, 0),
                                            (1, mb))[0]
                    sc = (cum.reshape(nseq, beam, 1)
                          + logp.reshape(nseq, beam, vocab))
                    # first expansion: every beam of a sequence is the
                    # same prompt — keep only beam 0's continuations
                    dup = jnp.logical_and(
                        safe_pos == plen - 1,
                        jnp.arange(beam)[None, :, None] > 0)
                    sc = jnp.where(dup, -jnp.inf, sc)
                    best, idx = lax.top_k(sc.reshape(nseq, beam * vocab),
                                          beam)
                    ids = (idx % vocab).reshape(mb)
                    par = (jnp.arange(nseq)[:, None] * beam
                           + idx // vocab).reshape(mb)
                    new_cum = best.reshape(mb)
                    # forced prompt steps keep identity/zero; bubbles keep
                    # the table untouched
                    forced = safe_pos < plen - 1
                    ids = jnp.where(forced, jnp.argmax(logits, -1), ids)
                    par = jnp.where(forced, jnp.arange(mb), par)
                    keep = jnp.logical_or(forced, jnp.logical_not(valid))
                    new_cum = jnp.where(keep, cum, new_cum)
                    caches = dict(caches, beam_cum=lax.dynamic_update_slice(
                        caches["beam_cum"], new_cum[None], (g, 0)))
                    a_out = a_out.at[:, self.d_model].set(
                        par.astype(jnp.float32))
                elif sample:
                    # keyed by the global step so results are identical
                    # under any dispatch chunking; rows draw independently
                    ids = _sample_ids(
                        logits, temp, top_k,
                        jax.random.fold_in(jax.random.PRNGKey(seed), t))
                else:
                    ids = jnp.argmax(logits, axis=-1)
                a_out = a_out.at[:, 0].set(ids.astype(jnp.float32))
            else:
                a_out = x.astype(jnp.float32)
                if beam > 1:
                    # pass the incoming parent column onward unchanged —
                    # every stage re-derives applicability from pos
                    a_out = jnp.concatenate(
                        [a_out, a[:, self.d_model:]], axis=-1)
            return a_out, caches

        return branch

    def _make_prefill_branch(self, s: int, plen: int, sample: bool,
                             top_k: int | None):
        """Stage ``s``'s pipelined-prefill step: one whole prompt group.

        The group's full [mb, plen] prompt flows through the stages like
        one inference microbatch; each block runs full-sequence causal
        attention (``apply_with_kv``) and bulk-writes cache rows
        ``0..plen-1``; the last stage emits the first generated token
        (position ``plen``).  Bubble steps (g outside [0, n)) write the
        scratch group ``n``.
        """
        n = self.num_stages
        nodes = self.graph.nodes
        cd = self.compute_dtype
        mb, d = self.microbatch, self.d_model
        is_first, is_last = s == 0, s == n - 1
        embed_op = self.embed_op
        int8 = self.kv_cache == "int8"

        def branch(w_local, a, caches, prompt, g, seed, temp):
            p = self._stage_params(s, w_local)
            valid = jnp.logical_and(g >= 0, g < n)
            safe_g = jnp.clip(g, 0, n - 1)
            write_g = jnp.where(valid, safe_g, n)  # scratch group

            if is_first:
                ids = lax.dynamic_slice(prompt, (safe_g, 0, 0),
                                        (1, mb, plen))[0]
                x = embed_op.apply(p["embeddings"], ids).astype(cd)
            else:
                x = a.reshape(mb, plen, d).astype(cd)

            kvh, hd = self.num_kv_heads, self.head_dim
            for l, nm in enumerate(self.stage_blocks[s]):
                op = nodes[nm].op
                x, k, v = op.apply_with_kv(p[nm], x)
                # head-major relayout (one transpose per prompt, amortized)
                k = k.reshape(mb, plen, kvh, hd).transpose(0, 2, 1, 3)
                v = v.reshape(mb, plen, kvh, hd).transpose(0, 2, 1, 3)
                if int8:
                    k, ks = op.quantize_row(k)   # [mb, kv, plen] scales
                    v, vs = op.quantize_row(v)
                    caches = dict(
                        caches,
                        ks=lax.dynamic_update_slice(
                            caches["ks"], ks[None, None],
                            (l, write_g, 0, 0, 0)),
                        vs=lax.dynamic_update_slice(
                            caches["vs"], vs[None, None],
                            (l, write_g, 0, 0, 0)))
                caches = dict(
                    caches,
                    k=lax.dynamic_update_slice(
                        caches["k"], k[None, None].astype(
                            caches["k"].dtype), (l, write_g, 0, 0, 0, 0)),
                    v=lax.dynamic_update_slice(
                        caches["v"], v[None, None].astype(
                            caches["v"].dtype), (l, write_g, 0, 0, 0, 0)))

            if is_last:
                h = nodes["final_ln"].op.apply(p["final_ln"], x[:, -1])
                logits = nodes["lm_head"].op.apply(
                    p["lm_head"], h).astype(jnp.float32)
                if sample:
                    # key domain disjoint from decode's per-step keys
                    ids = _sample_ids(
                        logits, temp, top_k,
                        jax.random.fold_in(jax.random.PRNGKey(seed),
                                           (1 << 30) + safe_g))
                else:
                    ids = jnp.argmax(logits, axis=-1)
                a_out = jnp.zeros((mb, plen * d), jnp.float32)
                a_out = a_out.at[:, 0].set(ids.astype(jnp.float32))
            else:
                a_out = x.reshape(mb, plen * d).astype(jnp.float32)
            return a_out, caches

        return branch

    def _state_specs(self):
        """shard_map spec pytree for the cache-state dict."""
        spec7 = P(STAGE_AXIS, None, None, None, None, None, None)
        specs = {"k": spec7, "v": spec7}
        if self.kv_cache == "int8":
            spec6 = P(STAGE_AXIS, None, None, None, None, None)
            specs.update(ks=spec6, vs=spec6)
        if self.beam_width > 1:
            # per-group cumulative beam scores; only the LAST stage's
            # device shard is meaningful (it runs the expansion)
            specs["beam_cum"] = P(STAGE_AXIS, None, None)
        return specs

    def _build_prefill_fn(self, plen: int, sample: bool, top_k: int | None):
        n = self.num_stages
        perm = [(k, (k + 1) % n) for k in range(n)]
        branches = [self._make_prefill_branch(s, plen, sample, top_k)
                    for s in range(n)]
        mb, d = self.microbatch, self.d_model
        num_steps = 2 * n - 1  # n groups through n stages, pipelined

        def device_prefill(w, prompt, seed, temp, caches):
            w_l = jax.tree.map(lambda x: x[0], w)
            idx = lax.axis_index(STAGE_AXIS)
            a0 = jnp.zeros((mb, plen * d), jnp.float32)
            local = jax.tree.map(lambda c: c[0], caches)

            def body(carry, t):
                a, caches = carry
                g = t - idx  # stage idx prefills group t - idx
                a_out, caches = lax.switch(
                    idx, branches, w_l, a, caches, prompt, g, seed, temp)
                a_next = lax.ppermute(a_out, STAGE_AXIS, perm)
                return (a_next, caches), a_next[:, 0]

            (_, local), ids = lax.scan(
                body, (a0, local), jnp.arange(num_steps, dtype=jnp.int32))
            return jax.tree.map(lambda c: c[None], local), ids[None]

        state = self._state_specs()
        fn = shard_map(
            device_prefill, mesh=self.mesh,
            in_specs=(self._wspec_tree, P(None, None, None), P(), P(),
                      state),
            out_specs=(state, P(STAGE_AXIS, None, None)),
            check_vma=False,
        )
        return jax.jit(fn, donate_argnums=(4,),
                       **ring_jit_kwargs(self.mesh.devices))

    def _init_state(self):
        """Fresh sharded pipeline state: ring carry + empty KV caches.

        The zero-fill programs are jitted ONCE and cached — a fresh lambda
        per call would recompile (~0.4 s each) on every ``generate``.
        """
        if self._init_fn is None:
            n, mb, d = self.num_stages, self.microbatch, self.d_model
            act_sh = NamedSharding(self.mesh, P(STAGE_AXIS, None, None))
            state_sh = jax.tree.map(
                lambda spec: NamedSharding(self.mesh, spec),
                self._state_specs())
            cdt = jnp.int8 if self.kv_cache == "int8" \
                else self.compute_dtype

            def zeros():
                caches = {"k": jnp.zeros((n,) + self._cache_shape, cdt),
                          "v": jnp.zeros((n,) + self._cache_shape, cdt)}
                if self.kv_cache == "int8":
                    caches["ks"] = jnp.zeros((n,) + self._scale_shape,
                                             jnp.float32)
                    caches["vs"] = jnp.zeros((n,) + self._scale_shape,
                                             jnp.float32)
                if self.beam_width > 1:
                    caches["beam_cum"] = jnp.zeros((n, n, mb), jnp.float32)
                return (jnp.zeros((n, mb, self._ring_width), jnp.float32),
                        caches)

            self._init_fn = jax.jit(
                zeros, out_shardings=(act_sh, state_sh))
        return self._init_fn()

    def _build_decode_fn(self, chunk_steps: int, sample: bool,
                         top_k: int | None):
        n = self.num_stages
        perm = [(k, (k + 1) % n) for k in range(n)]
        branches = [self._make_branch(s, sample, top_k) for s in range(n)]
        beam = self.beam_width > 1
        d = self.d_model

        def device_decode(w, prompt, plen, t0, t_stop, seed, temp,
                          first_ids, first_pos, start, a, caches):
            w_l = jax.tree.map(lambda x: x[0], w)
            idx = lax.axis_index(STAGE_AXIS)
            local = jax.tree.map(lambda c: c[0], caches)

            def body(carry, t):
                a, caches = carry
                # stage idx serves group (t - idx) mod n at token position
                # start + (t - idx)//n; negative skew = warmup bubble, and
                # chunk-overshoot steps (t >= t_stop) are bubbles too —
                # they must not touch caches or the beam ledger
                rel = t - idx
                live = jnp.logical_and(rel >= 0, t < t_stop)
                g = jnp.where(live, rel % n, 0)
                pos = jnp.where(live, start + rel // n, -1)
                a_out, caches = lax.switch(
                    idx, branches, w_l, a, caches, prompt, g, pos, plen,
                    t, seed, temp, first_ids, first_pos)
                a_next = lax.ppermute(a_out, STAGE_AXIS, perm)
                # emit what just arrived on the wrap link: ids (and, under
                # beam search, parent indices) chosen by the last stage,
                # readable on device 0 (runtime/spmd.py emits the same
                # slice for the inference pipeline)
                emit = (jnp.stack([a_next[:, 0], a_next[:, d]], axis=-1)
                        if beam else a_next[:, 0])
                return (a_next, caches), emit

            (a, local), ids = lax.scan(
                body, (a[0], local),
                t0 + jnp.arange(chunk_steps, dtype=jnp.int32))
            return (a[None], jax.tree.map(lambda c: c[None], local),
                    ids[None])

        state = self._state_specs()
        out_ids = P(STAGE_AXIS, None, None, None) if beam \
            else P(STAGE_AXIS, None, None)
        fn = shard_map(
            device_decode, mesh=self.mesh,
            in_specs=(self._wspec_tree, P(None, None, None), P(), P(),
                      P(), P(), P(), P(None, None), P(), P(),
                      P(STAGE_AXIS, None, None), state),
            out_specs=(P(STAGE_AXIS, None, None), state, out_ids),
            check_vma=False,
        )
        # donate the carried state so chunked dispatches update in place
        return jax.jit(fn, donate_argnums=(10, 11),
                       **ring_jit_kwargs(self.mesh.devices))

    # ------------------------------------------------------------------

    def _schedule(self, t_tok: int, start: int,
                  token_chunk: int | None) -> tuple[int, int]:
        """(num_steps, chunk_steps) for decoding positions (start, t_tok).

        The last needed step emits position t_tok-1 of the last group:
        ``(n-1) + n*(t_tok-2-start) + (n-1)``; one schedule shared by the
        greedy/sampling and beam paths."""
        n = self.num_stages
        num_steps = (n - 1) + n * (t_tok - 2 - start) + (n - 1) + 1 \
            if t_tok - 1 > start else 0
        chunk_steps = max(num_steps, n) if token_chunk is None \
            else max(n, n * int(token_chunk))
        return num_steps, chunk_steps

    def _get_decode_fn(self, chunk_steps: int, sample: bool,
                       top_k: int | None):
        key = (chunk_steps, sample, top_k)
        fn = self._decode_fns.get(key)
        if fn is None:
            fn = self._decode_fns[key] = \
                self._build_decode_fn(chunk_steps, sample, top_k)
        return fn

    def _gather_init(self, prompt: np.ndarray, plen: int, t_tok: int,
                     start: int,
                     first_ids: np.ndarray | None) -> tuple[np.ndarray, int]:
        """Token output skeleton + the first position decode steps fill."""
        n, mb = self.num_stages, self.microbatch
        out = np.zeros((n, mb, t_tok), np.int64)
        out[:, :, :plen] = prompt[:, :, :plen]
        if first_ids is not None and start < t_tok:
            out[:, :, start] = first_ids.astype(np.int64)
            return out, start + 1
        return out, max(1, plen)

    def _gather_into(self, out: np.ndarray, ids_steps: np.ndarray,
                     t0: int, t_tok: int, start: int, p0: int) -> None:
        """Scatter one chunk of emitted wrap-link ids into ``out``.

        Each decode scan step t >= n-1 emits exactly one (group, position):
        ``g = (t - (n-1)) % n``, ``p = start + 1 + (t - (n-1) - g) // n``
        — the inverse of "token p of group g is sampled at step
        (n-1) + n*(p-1-start) + g".  O(chunk) per call, so chunked EOS
        checking stays linear in the total step count.
        """
        n = self.num_stages
        for i in range(ids_steps.shape[0]):
            t = t0 + i
            if t < n - 1:
                continue
            g = (t - (n - 1)) % n
            p = start + 1 + (t - (n - 1) - g) // n
            if p0 <= p < t_tok:
                out[g, :, p] = ids_steps[i].astype(np.int64)

    def generate(self, prompt_ids: np.ndarray, max_new_tokens: int, *,
                 temperature: float = 0.0, top_k: int | None = None,
                 seed: int = 0, eos_id: int | None = None,
                 token_chunk: int | None = None,
                 prefill: bool = False,
                 on_tokens=None) -> np.ndarray:
        """Decode ``max_new_tokens`` past each prompt.

        ``prompt_ids``: [B, prompt_len] ints, B % microbatch == 0; batches
        beyond one pipeline fill (num_stages * microbatch) are processed
        in successive full-pipe rounds.  All prompts share one length
        (pad/bucket upstream).  Returns [B, prompt_len + max_new_tokens].

        ``temperature=0`` is greedy argmax; ``temperature>0`` samples the
        softmax (optionally truncated to ``top_k``), keyed by
        ``(seed, step)`` so results do not depend on dispatch chunking.
        ``token_chunk`` splits the scan into dispatches of that many tokens
        per group (one compiled program serves every generation length);
        the default is the whole generation in one dispatch.  ``eos_id``
        stops early once every sequence has emitted it and fills the tail
        with ``eos_id``.

        ``prefill=True`` seeds the KV caches with a fused full-sequence
        pipelined pass (each group's whole prompt crosses each stage in
        ONE causal-attention step) instead of decode-rate teacher forcing:
        prompt cost drops from ``plen * n`` ring steps to ``2n - 1``.
        Greedy results are identical up to float reduction order; sampled
        results use a different key for the first generated token.

        ``on_tokens(lo, hi, tokens, rows=(r0, r1))`` streams newly
        decodable positions to the caller after each chunk dispatch:
        ``tokens`` is [r1-r0, hi-lo] for positions [lo, hi) of sequence
        rows [r0, r1) (generated region only; rows=(0, B) unless the
        batch spans several pipeline-fill rounds) — pair with
        ``token_chunk`` for incremental delivery.  With ``eos_id``,
        streamed tokens past a sequence's EOS are garbage the final
        result replaces with ``eos_id``.
        """
        prompt_ids = np.asarray(prompt_ids)
        if prompt_ids.ndim != 2:
            raise ValueError("prompt_ids must be [B, prompt_len]")
        b, plen = prompt_ids.shape
        if plen < 1:
            raise ValueError("prompt must contain at least one token "
                             "(position 0 has nothing to condition on)")
        n, mb = self.num_stages, self.microbatch
        if self.beam_width > 1:
            if prefill or eos_id is not None or float(temperature) > 0:
                raise ValueError(
                    "beam search currently composes with neither prefill, "
                    "eos_id, nor temperature sampling")
            if on_tokens is not None:
                raise ValueError(
                    "beam search cannot stream tokens (sequences are only "
                    "final after the last re-parenting)")
            return self._generate_beam(prompt_ids, max_new_tokens,
                                       token_chunk=token_chunk)
        if b % mb or b == 0:
            raise ValueError(
                f"B={b} must be a non-zero multiple of microbatch={mb}")
        if b > n * mb:
            # more sequences than one pipeline fill: successive rounds.
            # Each round derives its own seed — otherwise identical
            # prompts in different rounds would sample identical
            # continuations (the step keys restart at t=0 every round).
            # Streaming callers see each round's spans in turn; the
            # rows kwarg identifies the round's sequence range.
            outs = []
            for lo in range(0, b, n * mb):
                cb = None
                if on_tokens is not None:
                    def cb(a, c, t, rows, _lo=lo):  # noqa: E306
                        on_tokens(a, c, t,
                                  rows=(_lo + rows[0], _lo + rows[1]))
                outs.append(self.generate(
                    prompt_ids[lo: lo + n * mb], max_new_tokens,
                    temperature=temperature, top_k=top_k, seed=seed + lo,
                    eos_id=eos_id, token_chunk=token_chunk,
                    prefill=prefill, on_tokens=cb))
            return np.concatenate(outs, axis=0)
        t_tok = plen + max_new_tokens
        if t_tok > self.max_len:
            raise ValueError(
                f"prompt_len + max_new_tokens = {t_tok} exceeds "
                f"max_len={self.max_len}")

        prompt = np.zeros((n, mb, plen), np.int32)
        prompt.reshape(n * mb, plen)[:b] = prompt_ids
        if t_tok == plen:
            return prompt.reshape(n * mb, plen)[:b].astype(np.int64)
        sample = float(temperature) > 0.0
        if not sample:
            top_k = None  # unused by argmax; keep the program caches keyed
            # identically so greedy calls never recompile over it
        prompt_dev = jnp.asarray(prompt)
        plen_s = jnp.int32(plen)
        seed_s = jnp.uint32(seed)
        temp_s = jnp.float32(temperature)
        a, caches = self._init_state()

        if prefill:
            pkey = (plen, sample, top_k)
            pfn = self._prefill_fns.get(pkey)
            if pfn is None:
                pfn = self._prefill_fns[pkey] = \
                    self._build_prefill_fn(plen, sample, top_k)
            caches, pre_ids = pfn(self._w, prompt_dev, seed_s, temp_s,
                                  caches)
            # group g's first generated token exits the wrap link at
            # prefill step g + (n-1)
            pre_np = np.asarray(pre_ids[0])
            first_ids_np = np.stack(
                [pre_np[g + n - 1] for g in range(n)]).astype(np.int32)
            start = plen
        else:
            first_ids_np = None
            start = 0

        # with prefill, position `start` is already known (first_ids)
        num_steps, chunk_steps = self._schedule(t_tok, start, token_chunk)
        fn = self._get_decode_fn(chunk_steps, sample, top_k)

        fi_dev = jnp.asarray(first_ids_np if first_ids_np is not None
                             else np.zeros((n, mb), np.int32))
        fp_s = jnp.int32(plen if prefill else -1)
        start_s = jnp.int32(start)
        chunks: list = []  # device chunks (batch path), drained at the end
        out3, p0 = self._gather_init(prompt, plen, t_tok, start,
                                     first_ids_np)
        incremental = eos_id is not None or on_tokens is not None
        p_done = plen - 1  # last position already delivered to on_tokens
        if on_tokens is not None and prefill and t_tok > plen:
            # the prefill already produced position plen (first_ids)
            flat = out3.reshape(n * mb, t_tok)[:b]
            on_tokens(plen, plen + 1, flat[:, plen: plen + 1].copy(),
                      rows=(0, b))
            p_done = plen
        steps_run = 0
        dec_count = REGISTRY.counter("decode.dispatches")
        dec_hist = REGISTRY.histogram("decode.dispatch_s")
        tr = tracer()
        while steps_run < num_steps:
            t0_disp = time.perf_counter()
            a, caches, ids = fn(self._w, prompt_dev, plen_s,
                                jnp.int32(steps_run), jnp.int32(num_steps),
                                seed_s, temp_s, fi_dev, fp_s, start_s,
                                a, caches)
            dt_disp = time.perf_counter() - t0_disp
            dec_count.n += 1
            dec_hist.record(dt_disp)
            if tr.enabled:
                tr.record("decode.chunk", t0_disp, dt_disp,
                          {"steps_run": steps_run,
                           "chunk_steps": chunk_steps})
            if incremental:
                # incremental scatter of just this chunk: linear host work
                self._gather_into(out3, np.asarray(ids[0]), steps_run,
                                  t_tok, start, p0)
            else:
                chunks.append(ids)
            steps_run += chunk_steps
            if incremental:
                # positions already decodable for EVERY group this far
                p_avail = start + min(
                    (steps_run - 1 - (n - 1) - g) // n + 1
                    for g in range(n))
                p_avail = min(p_avail, t_tok - 1)
                flat = out3.reshape(n * mb, t_tok)[:b]
                if on_tokens is not None and p_avail > p_done \
                        and p_avail >= plen:
                    lo = max(p_done + 1, plen)
                    on_tokens(lo, p_avail + 1,
                              flat[:, lo: p_avail + 1].copy(),
                              rows=(0, b))
                    p_done = p_avail
                if eos_id is not None and p_avail >= plen and np.all(
                        (flat[:, plen: p_avail + 1] == eos_id).any(axis=1)):
                    break
        for i, c in enumerate(chunks):  # non-incremental: one pass at the end
            self._gather_into(out3, np.asarray(c[0]), i * chunk_steps,
                              t_tok, start, p0)
        out = out3.reshape(n * mb, t_tok)[:b]
        if eos_id is not None:
            # freeze everything after each sequence's first generated EOS
            gen = out[:, plen:]
            hit = gen == eos_id
            first = np.where(hit.any(1), hit.argmax(1), gen.shape[1])
            mask = np.arange(gen.shape[1])[None, :] > first[:, None]
            gen[mask] = eos_id
        return out

    def _generate_beam(self, prompt_ids: np.ndarray, max_new_tokens: int,
                       *, token_chunk: int | None) -> np.ndarray:
        """Pipelined beam search; returns each prompt's best sequence.

        Each prompt occupies ``beam_width`` adjacent microbatch rows.  The
        last stage expands beams (top ``beam`` of beam*V continuations by
        cumulative log-probability, duplicate-masked on the first
        expansion) and the chosen parent indices ride the ring's extra
        column so every stage re-parents its cache rows before appending
        (see ``_make_branch``).  The host backtracks the recorded
        (token, parent) pairs and picks the best final beam per prompt.
        """
        n, mb, beam = self.num_stages, self.microbatch, self.beam_width
        b, plen = prompt_ids.shape
        nspg = mb // beam  # sequences per group
        if b % nspg or b == 0:
            raise ValueError(
                f"B={b} must be a non-zero multiple of "
                f"microbatch/beam_width = {nspg}")
        if b > n * nspg:
            return np.concatenate(
                [self._generate_beam(prompt_ids[lo: lo + n * nspg],
                                     max_new_tokens,
                                     token_chunk=token_chunk)
                 for lo in range(0, b, n * nspg)], axis=0)
        t_tok = plen + max_new_tokens
        if t_tok > self.max_len:
            raise ValueError(
                f"prompt_len + max_new_tokens = {t_tok} exceeds "
                f"max_len={self.max_len}")

        # each prompt duplicated over its beam rows
        rows = np.repeat(prompt_ids, beam, axis=0)
        prompt = np.zeros((n, mb, plen), np.int32)
        prompt.reshape(n * mb, plen)[: rows.shape[0]] = rows
        if t_tok == plen:
            return prompt_ids.astype(np.int64)

        num_steps, chunk_steps = self._schedule(t_tok, 0, token_chunk)
        fn = self._get_decode_fn(chunk_steps, False, None)

        prompt_dev = jnp.asarray(prompt)
        zero = jnp.int32(0)
        fi_dev = jnp.zeros((n, mb), jnp.int32)
        a, caches = self._init_state()
        chunks = []
        steps_run = 0
        while steps_run < num_steps:
            a, caches, ids = fn(self._w, prompt_dev, jnp.int32(plen),
                                jnp.int32(steps_run), jnp.int32(num_steps),
                                jnp.uint32(0), jnp.float32(0.0), fi_dev,
                                jnp.int32(-1), zero, a, caches)
            chunks.append(ids)
            steps_run += chunk_steps
        arr = np.concatenate([np.asarray(c[0]) for c in chunks], axis=0)
        toks = np.round(arr[..., 0]).astype(np.int64)   # [T, mb]
        pars = np.round(arr[..., 1]).astype(np.int64)
        # final cumulative scores live on the last stage's shard
        cum = np.asarray(caches["beam_cum"])[n - 1]      # [n_groups, mb]

        out = np.zeros((b, t_tok), np.int64)
        out[:, :plen] = prompt_ids
        for s in range(b):
            g, si = divmod(s, nspg)
            row_lo = si * beam
            r = row_lo + int(np.argmax(cum[g, row_lo: row_lo + beam]))
            for p in range(t_tok - 1, plen - 1, -1):
                t = (n - 1) + n * (p - 1) + g
                out[s, p] = toks[t, r]
                r = int(pars[t, r])
        return out
