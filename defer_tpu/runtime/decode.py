"""Pipelined autoregressive decoding with per-stage KV caches.

The inference engine (:mod:`defer_tpu.runtime.spmd`) streams independent
inputs through the stage ring; generation is harder — token t+1 of a
sequence cannot enter stage 0 until token t has left the last stage.  A
single sequence would therefore keep only one of N stages busy.  The classic
fix, implemented here: interleave N independent *groups* of sequences
round-robin, so at every step stage k serves group ``(t - k) mod N`` — the
ring is full and every device computes every step, DEFER's "all stages busy
on different in-flight inputs" (SURVEY.md §0) transposed to token time.

TPU-native design, one SPMD program:

  * Weights: each device materializes only its stage's parameters from a
    stage-sharded flat buffer (same scheme as ``SpmdPipeline``).
  * KV caches: a per-device resident array ``[Lmax, N, mb, max_len+1, d]``
    (local blocks x groups) in compute dtype; row ``max_len`` is a scratch
    slot that warmup bubbles write into, so no masked read-modify-write of
    the cache is ever needed.
  * The ring carry is one ``[mb, d]`` float32 buffer per device: stage
    activations in flight, and — on the wrap link from the last stage back
    to stage 0 (the reference's node->dispatcher link,
    src/dispatcher.py:51-55) — the greedily sampled token ids encoded in
    column 0 (f32 is exact for ids < 2^24).
  * ``lax.scan`` over decode steps fuses the whole token loop into one XLA
    dispatch; prompt teacher-forcing happens inside the scan (stage 0
    substitutes the known prompt token while ``pos < prompt_len``), so
    prefill and generation are one program with zero host round trips.

Scope (v1): greedy argmax sampling, stage-axis-only mesh, the ``gpt()``
node-name contract (``embeddings`` / ``block_i`` / ``final_ln`` /
``lm_head`` — models/gpt.py).  Prefill advances one token per group per N
steps (decode-rate); a fused full-sequence prefill can seed the caches in a
later revision.
"""

from __future__ import annotations

from typing import Any

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..graph.ir import LayerGraph
from ..models.gpt import CausalTransformerBlock, GptEmbedding
from ..parallel.mesh import STAGE_AXIS, pipeline_mesh
from . import flatbuf


def _split_blocks(num_blocks: int, num_stages: int) -> list[list[int]]:
    """Contiguous, balanced block assignment (stage i gets ~L/N blocks)."""
    bounds = [round(num_blocks * s / num_stages)
              for s in range(num_stages + 1)]
    out = [list(range(bounds[s], bounds[s + 1])) for s in range(num_stages)]
    if any(not b for b in out):
        raise ValueError(
            f"{num_blocks} blocks cannot fill {num_stages} stages")
    return out


class PipelinedDecoder:
    """Greedy autoregressive generation over a ``stage``-axis mesh.

    Usage::

        graph = gpt_tiny()
        dec = PipelinedDecoder(graph, graph.init(key), num_stages=4,
                               microbatch=2, max_len=32)
        tokens = dec.generate(prompt_ids, max_new_tokens=16)

    ``prompt_ids`` is [B, prompt_len] with B <= num_stages * microbatch;
    returns [B, prompt_len + max_new_tokens].
    """

    def __init__(
        self,
        graph: LayerGraph,
        params: dict[str, Any],
        *,
        num_stages: int,
        max_len: int,
        mesh: Mesh | None = None,
        microbatch: int = 1,
        compute_dtype=None,
    ):
        self.graph = graph
        self.num_stages = n = num_stages
        self.mesh = mesh if mesh is not None else pipeline_mesh(n)
        if self.mesh.shape[STAGE_AXIS] != n:
            raise ValueError(
                f"mesh stage axis {self.mesh.shape[STAGE_AXIS]} != {n}")
        self.microbatch = mb = microbatch
        self.max_len = max_len
        self.compute_dtype = jnp.dtype(compute_dtype) if compute_dtype \
            else jnp.dtype(jnp.float32)

        nodes = graph.nodes
        for req in ("embeddings", "final_ln", "lm_head"):
            if req not in nodes:
                raise ValueError(
                    f"decoder graphs must follow the gpt() node contract; "
                    f"missing {req!r} (models/gpt.py)")
        self.embed_op: GptEmbedding = nodes["embeddings"].op
        if max_len > self.embed_op.max_len:
            raise ValueError(
                f"max_len {max_len} exceeds the model's positional table "
                f"({self.embed_op.max_len})")
        block_names = [nm for nm in graph.topo_order
                       if nm.startswith("block_")]
        self.block_names = block_names
        for nm in block_names:
            if not isinstance(nodes[nm].op, CausalTransformerBlock):
                raise TypeError(f"{nm} is not a CausalTransformerBlock")
        self.d_model = nodes[block_names[0]].out_spec.shape[-1]
        self.vocab = nodes["lm_head"].out_spec.shape[-1]

        assign = _split_blocks(len(block_names), n)
        self.stage_blocks = [[block_names[i] for i in idxs]
                             for idxs in assign]
        self.l_max = max(len(b) for b in self.stage_blocks)

        # --- stage-sharded flat weight buffer (scheme of runtime/spmd.py)
        stage_param_names: list[list[str]] = []
        for s in range(n):
            names = list(self.stage_blocks[s])
            if s == 0:
                names.insert(0, "embeddings")
            if s == n - 1:
                names += ["final_ln", "lm_head"]
            stage_param_names.append(names)
        self._stage_param_names = stage_param_names

        self._wmeta, self._wtreedef, flats = [], [], []
        for names in stage_param_names:
            sub = {nm: params[nm] for nm in names}
            leaves, treedef = jax.tree.flatten(sub)
            leaves = [np.asarray(l, np.float32) for l in leaves]
            self._wmeta.append(flatbuf.leaf_meta(leaves))
            self._wtreedef.append(treedef)
            flats.append(flatbuf.pack_leaves(leaves, np.float32))
        self._w = jax.device_put(
            flatbuf.stack_rows(flats, np.float32),
            NamedSharding(self.mesh, P(STAGE_AXIS, None)))

        self._branches = [self._make_branch(s) for s in range(n)]
        self._cache_shape = (self.l_max, n, mb, max_len + 1, self.d_model)
        #: compiled decode programs keyed by scan length — repeat
        #: ``generate`` calls of the same shape are dispatch-only
        self._decode_fns: dict[int, Any] = {}

    # ------------------------------------------------------------------

    def _stage_params(self, s: int, w_local: jax.Array):
        return flatbuf.unpack_leaves(w_local, self._wmeta[s],
                                     self._wtreedef[s])

    def _make_branch(self, s: int):
        """Stage ``s``'s step: consume the ring buffer, update caches.

        Uniform signature for ``lax.switch``:
        ``(w_local, a, kc, vc, prompt, g, pos, plen) -> (a_out, kc, vc)``.
        """
        n = self.num_stages
        nodes = self.graph.nodes
        cd = self.compute_dtype
        is_first, is_last = s == 0, s == n - 1
        block_ops = [nodes[nm].op for nm in self.stage_blocks[s]]
        embed_op = self.embed_op

        def branch(w_local, a, kc, vc, prompt, g, pos, plen):
            p = self._stage_params(s, w_local)
            # bubble steps (pos < 0 during warmup skew) write the cache
            # scratch row and attend over nothing real; their outputs are
            # never read (host drops them by schedule index)
            valid = pos >= 0
            safe_pos = jnp.clip(pos, 0, self.max_len - 1)
            write_pos = jnp.where(valid, safe_pos, self.max_len)

            if is_first:
                recv_ids = jnp.round(a[:, 0]).astype(jnp.int32)
                prompt_ids = lax.dynamic_slice(
                    prompt, (g, 0, jnp.minimum(safe_pos, prompt.shape[2] - 1)),
                    (1, self.microbatch, 1))[0, :, 0]
                ids = jnp.where(safe_pos < plen, prompt_ids, recv_ids)
                x = embed_op.embed_at(p["embeddings"], ids, safe_pos)
                x = x.astype(cd)
            else:
                x = a[:, : self.d_model].astype(cd)

            for l, (nm, op) in enumerate(zip(self.stage_blocks[s],
                                             block_ops)):
                k_l = lax.dynamic_slice(
                    kc, (l, g, 0, 0, 0),
                    (1, 1) + self._cache_shape[2:])[0, 0]
                v_l = lax.dynamic_slice(
                    vc, (l, g, 0, 0, 0),
                    (1, 1) + self._cache_shape[2:])[0, 0]
                x, k_l, v_l = op.decode(p[nm], x, k_l, v_l, write_pos)
                kc = lax.dynamic_update_slice(
                    kc, k_l[None, None], (l, g, 0, 0, 0))
                vc = lax.dynamic_update_slice(
                    vc, v_l[None, None], (l, g, 0, 0, 0))

            if is_last:
                h = nodes["final_ln"].op.apply(p["final_ln"], x)
                logits = nodes["lm_head"].op.apply(p["lm_head"], h)
                ids = jnp.argmax(logits.astype(jnp.float32), axis=-1)
                a_out = jnp.zeros((self.microbatch, self.d_model),
                                  jnp.float32)
                a_out = a_out.at[:, 0].set(ids.astype(jnp.float32))
            else:
                a_out = x.astype(jnp.float32)
            return a_out, kc, vc

        return branch

    def _build_decode_fn(self, num_steps: int):
        n = self.num_stages
        perm = [(k, (k + 1) % n) for k in range(n)]
        branches = self._branches
        cd = self.compute_dtype
        mb, d = self.microbatch, self.d_model

        def device_decode(w, prompt, plen):
            w_l = w[0]
            idx = lax.axis_index(STAGE_AXIS)
            a0 = jnp.zeros((mb, d), jnp.float32)
            kc0 = jnp.zeros(self._cache_shape, cd)
            vc0 = jnp.zeros(self._cache_shape, cd)

            def body(carry, t):
                a, kc, vc = carry
                # stage idx serves group (t - idx) mod n at token position
                # (t - idx) // n; negative during the warmup skew = bubble
                rel = t - idx
                g = jnp.where(rel >= 0, rel % n, 0)
                pos = jnp.where(rel >= 0, rel // n, -1)
                a_out, kc, vc = lax.switch(
                    idx, branches, w_l, a, kc, vc, prompt, g, pos, plen)
                a_next = lax.ppermute(a_out, STAGE_AXIS, perm)
                # emit what just arrived on the wrap link: ids sampled by
                # the last stage, readable on device 0 (runtime/spmd.py
                # emits the same slice for the inference pipeline)
                return (a_next, kc, vc), a_next[:, 0]

            (_, _, _), ids = lax.scan(
                body, (a0, kc0, vc0), jnp.arange(num_steps, dtype=jnp.int32))
            return ids[None]  # [1, T, mb] per device

        fn = jax.shard_map(
            device_decode, mesh=self.mesh,
            in_specs=(P(STAGE_AXIS, None), P(None, None, None), P()),
            out_specs=P(STAGE_AXIS, None, None),
            check_vma=False,
        )
        return jax.jit(fn)

    # ------------------------------------------------------------------

    def generate(self, prompt_ids: np.ndarray, max_new_tokens: int,
                 ) -> np.ndarray:
        """Greedy-decode ``max_new_tokens`` past each prompt.

        ``prompt_ids``: [B, prompt_len] ints, B <= num_stages * microbatch
        and B % microbatch == 0.  All prompts share one length (pad/bucket
        upstream).  Returns [B, prompt_len + max_new_tokens].
        """
        prompt_ids = np.asarray(prompt_ids)
        if prompt_ids.ndim != 2:
            raise ValueError("prompt_ids must be [B, prompt_len]")
        b, plen = prompt_ids.shape
        if plen < 1:
            raise ValueError("prompt must contain at least one token "
                             "(position 0 has nothing to condition on)")
        n, mb = self.num_stages, self.microbatch
        if b % mb or not 0 < b <= n * mb:
            raise ValueError(
                f"B={b} must be a multiple of microbatch={mb} and at most "
                f"num_stages*microbatch={n * mb}")
        t_tok = plen + max_new_tokens
        if t_tok > self.max_len:
            raise ValueError(
                f"prompt_len + max_new_tokens = {t_tok} exceeds "
                f"max_len={self.max_len}")
        groups = b // mb

        prompt = np.zeros((n, mb, plen), np.int32)
        prompt.reshape(n * mb, plen)[:b] = prompt_ids
        # token at position p of group g is sampled by the last stage at
        # scan step (n-1) + n*(p-1) + g and emitted that same step; the
        # final needed position is t_tok - 1
        num_steps = (n - 1) + n * (t_tok - 2) + (n - 1) + 1 if t_tok > 1 \
            else n
        fn = self._decode_fns.get(num_steps)
        if fn is None:
            fn = self._decode_fns[num_steps] = \
                self._build_decode_fn(num_steps)
        ids = np.asarray(jax.device_get(
            fn(self._w, jnp.asarray(prompt), jnp.int32(plen))))[0]
        # ids: [T, mb] from device 0's wrap link
        out = np.zeros((n, mb, t_tok), np.int64)
        out[:, :, :plen] = prompt[:, :, :plen]
        for g in range(groups):
            for p in range(max(1, plen), t_tok):
                t = (n - 1) + n * (p - 1) + g
                out[g, :, p] = ids[t].astype(np.int64)
        return out.reshape(n * mb, t_tok)[:b]
