"""Dispatcher: the user-facing API (capability parity with the reference).

The reference's single entry point is
``DEFER(computeNodes).run_defer(model, partition_layers, input_stream,
output_stream)`` (src/dispatcher.py:107-115): it partitions, ships
sub-models to TCP nodes, then streams a queue of inputs through the chain
and surfaces results on an output queue.  The TPU-native ``Defer`` keeps the
same shape — queue in, queue out, streaming forever until told to stop — but
placement is a device mesh instead of IPs, and all data movement is
ICI/HBM-side (zero CPU-side tensor serialization, per BASELINE.md).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Iterable, Iterator

import numpy as np

import jax
import jax.numpy as jnp

from ..graph.ir import LayerGraph
from ..parallel.mesh import pipeline_mesh
from ..partition.partitioner import partition
from ..utils.config import DeferConfig
from .mpmd import MpmdPipeline
from .spmd import SpmdPipeline

#: sentinel a producer puts on the input queue to end the stream
END_OF_STREAM = None


class DeferHandle:
    """Handle to a running streaming deployment (returned by ``run_defer``)."""

    def __init__(self, thread: threading.Thread, pipeline,
                 stop_event: threading.Event):
        self._thread = thread
        self.pipeline = pipeline
        self._stop = stop_event
        #: exception that killed the serve thread, if any
        self.error: BaseException | None = None
        #: monotonic time the serve thread entered its current device
        #: dispatch, or None while idle (used by the watchdog)
        self._busy_since: float | None = None
        #: completed dispatches; the watchdog only arms after the first one
        #: so jit compilation time is never mistaken for a hang
        self._dispatches: int = 0
        #: slowest completed dispatch (seconds) — scales the watchdog
        #: threshold so legitimately slow deployments never false-positive
        self._max_dispatch_s: float = 0.0

    def stop(self):
        self._stop.set()

    @property
    def healthy(self) -> bool:
        """False once the serve thread died or was declared hung."""
        return self.error is None

    def join(self, timeout: float | None = None):
        """Wait for the serve thread; re-raises any error it died with.

        Raises as soon as ``error`` is set rather than waiting for thread
        exit: when the watchdog declares the deployment dead, the serve
        thread may be permanently wedged inside a device dispatch — exactly
        the case where an unbounded ``Thread.join`` would never return.
        """
        deadline = None if timeout is None else time.monotonic() + timeout
        while self.error is None and self._thread.is_alive():
            step = 0.25
            if deadline is not None:
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                step = min(step, left)
            self._thread.join(step)
        if self.error is not None:
            raise RuntimeError("defer dispatcher thread failed") from self.error

    @property
    def metrics(self):
        return self.pipeline.metrics


class Defer:
    """TPU-native DEFER deployment.

    ``mesh`` plays the role of the reference's ``computeNodes`` IP list
    (src/dispatcher.py:21): it names the devices that will host pipeline
    stages.
    """

    def __init__(self, mesh=None, config: DeferConfig | None = None):
        self.mesh = mesh
        self.config = config or DeferConfig()

    def _default_num_stages(self) -> int:
        """Stage count from this deployment's mesh (1 when mesh-less).

        The single lookup both :meth:`generate` and :meth:`score` use — a
        mesh without a stage axis errors clearly instead of silently
        running single-stage."""
        from ..parallel.mesh import STAGE_AXIS
        if self.mesh is None:
            return 1
        if STAGE_AXIS not in self.mesh.shape:
            raise ValueError(
                f"mesh has no {STAGE_AXIS!r} axis; pass num_stages or a "
                "pipeline_mesh")
        return self.mesh.shape[STAGE_AXIS]

    # -- construction ------------------------------------------------------

    def build(self, graph: LayerGraph, params: dict[str, Any],
              cut_points: list[str] | None = None,
              num_stages: int | None = None):
        """Partition + compile; returns the pipeline engine."""
        cfg = self.config
        stages = partition(graph, cut_points, num_stages=num_stages)
        if cfg.mode == "mpmd":
            devices = None
            if self.mesh is not None:
                devices = list(self.mesh.devices.flatten())
            return MpmdPipeline(stages, params, devices=devices,
                                microbatch=cfg.microbatch,
                                compute_dtype=cfg.compute_dtype)
        mesh = self.mesh
        if mesh is None:
            mesh = pipeline_mesh(len(stages), cfg.data_parallel,
                                 cfg.tensor_parallel)
        return SpmdPipeline(
            stages, params, mesh=mesh,
            microbatch=cfg.microbatch, chunk=cfg.chunk,
            buffer_dtype=jnp.dtype(cfg.buffer_dtype),
            compute_dtype=cfg.compute_dtype,
            wire=cfg.wire,
            master_weights=cfg.master_weights,
        )

    def generate(self, graph, params, prompt_ids, max_new_tokens: int,
                 *, num_stages: int | None = None, max_len: int | None = None,
                 kv_cache: str = "buffer", **sample_kw):
        """Pipelined autoregressive generation (decoder graphs).

        Convenience over :class:`~defer_tpu.runtime.decode.PipelinedDecoder`
        with this deployment's mesh/config: partitions the causal graph's
        blocks over ``num_stages`` (default: the mesh's stage axis, or 1),
        decodes ``max_new_tokens`` past each prompt.  ``sample_kw`` passes
        through (temperature, top_k, seed, eos_id, token_chunk, prefill).
        """
        from .decode import PipelinedDecoder
        if num_stages is None:
            num_stages = self._default_num_stages()
        dec = PipelinedDecoder(
            graph, params, num_stages=num_stages, mesh=self.mesh,
            microbatch=self.config.microbatch, max_len=max_len,
            compute_dtype=self.config.compute_dtype, kv_cache=kv_cache)
        return dec.generate(np.asarray(prompt_ids), max_new_tokens,
                            **sample_kw)

    def score(self, graph, params, ids, *, cut_points=None,
              num_stages: int | None = None):
        """Per-sequence log-likelihood of token ids under a causal LM.

        ``ids``: [B, T] ints (B % microbatch == 0).  Runs the
        full-sequence causal graph through the ordinary inference
        pipeline and sums next-token log-probabilities.  Returns
        ``(logprob [B], perplexity [B])`` — the evaluation-side companion
        of :meth:`generate`.
        """
        ids = np.asarray(ids)
        if ids.ndim != 2:
            raise ValueError("ids must be [B, T]")
        b, t = ids.shape
        mb = self.config.microbatch
        if b % mb or b == 0:
            raise ValueError(
                f"B={b} must be a non-zero multiple of microbatch={mb}")
        if cut_points is None and num_stages is None:
            num_stages = self._default_num_stages()
        pipe = self.build(graph, params, cut_points, num_stages)
        t_model = pipe.in_spec.shape[0]
        if t > t_model:
            raise ValueError(
                f"sequence length {t} exceeds the model's {t_model}")
        # causal attention: right-padding cannot influence positions < t,
        # so pad to the graph's fixed length and score the real prefix
        padded = np.zeros((b, t_model), ids.dtype)
        padded[:, :t] = ids
        logits = pipe.run(
            padded.reshape(b // mb, mb, t_model).astype(np.float32))
        logits = logits.reshape(b, t_model, -1)[:, :t]
        logp = jax.nn.log_softmax(jnp.asarray(logits, jnp.float32), axis=-1)
        tgt = jnp.asarray(ids[:, 1:], jnp.int32)
        pick = jnp.take_along_axis(logp[:, :-1], tgt[..., None], -1)[..., 0]
        total = np.asarray(pick.sum(axis=-1))
        ppl = np.exp(-total / (t - 1)) if t > 1 else np.ones(b)
        return total, ppl

    # -- health ------------------------------------------------------------

    def health_check(self, graph, params, cut_points=None, num_stages=None):
        """Compile-and-run probe of a deployment before serving traffic.

        Builds the pipeline, pushes one all-bubble chunk through the
        compiled program, and reports per-deployment status — the "health
        check on stage program compilation" the reference lacks entirely
        (SURVEY.md §5: a bad partition there only surfaces when a node
        crashes mid-stream).  Raises nothing: failures come back in the
        report so callers can decide.
        """
        report: dict[str, Any] = {"ok": False, "stages": None,
                                  "mesh": None, "error": None}
        try:
            pipe = self.build(graph, params, cut_points, num_stages)
            report["stages"] = len(pipe.stages)
            if getattr(pipe, "mesh", None) is not None:
                report["mesh"] = dict(pipe.mesh.shape)
            if isinstance(pipe, MpmdPipeline):
                x = np.zeros((1, pipe.microbatch) + pipe.in_spec.shape,
                             np.float32)
                pipe.run(x)
            else:
                # full-chunk bubble probe: the compiled artifact exercised
                # here is the exact [chunk, ...] program that will serve
                # traffic (a [1, ...] probe would compile a different
                # program and miss chunk-shape-specific failures)
                pipe.warmup()
            report["ok"] = True
        except Exception as e:  # noqa: BLE001 — report, don't raise
            report["error"] = e
        return report

    # -- batch API ---------------------------------------------------------

    def run(self, graph, params, inputs, cut_points=None, num_stages=None):
        """One-shot batched inference over the pipeline."""
        pipe = self.build(graph, params, cut_points, num_stages)
        return pipe.run(inputs)

    # -- streaming APIs ----------------------------------------------------

    def stream(self, graph, params, inputs: Iterable[np.ndarray],
               cut_points=None, num_stages=None) -> Iterator[np.ndarray]:
        """Generator streaming: yields one output per input microbatch."""
        pipe = self.build(graph, params, cut_points, num_stages)
        if isinstance(pipe, MpmdPipeline):
            for x in inputs:
                yield pipe.run(x[None])[0]
            return
        pipe.reset()
        batch: list[np.ndarray] = []
        for x in inputs:
            batch.append(x)
            if len(batch) == pipe.chunk:
                yield from pipe.push(np.stack(batch))
                batch.clear()
        if batch:
            pad = [np.zeros_like(batch[0])] * (pipe.chunk - len(batch))
            yield from pipe.push(np.stack(batch + pad), n_real=len(batch))
        yield from pipe.flush()

    def serve_endpoint(self, graph, params, cut_points=None, *,
                       num_stages=None, host: str = "127.0.0.1",
                       port: int = 0, codec: str = "raw",
                       stall_timeout_s: float = 120.0):
        """Network front door: accept framed tensors, stream them through
        the pipeline via the native staging ring, reply in order.

        This is the reference dispatcher's whole socket data plane
        (src/dispatcher.py:85-105) as one endpoint: a reader thread pushes
        incoming samples into the bounded native ring
        (``transport/staging.py``); the serve loop drains whole chunk
        blocks already laid out like the device transfer buffer and feeds
        the SPMD engine; results flow back on the same connection.
        Returns ``(server_address, thread)``; the thread exits after the
        client's END frame has been fully drained and echoed.
        """
        import socket as _socket

        from ..transport.framed import (K_END, K_TENSOR, recv_frame,
                                        send_end, send_frame)
        from ..transport.staging import HostStagingRing

        pipe = self.build(graph, params, cut_points, num_stages)
        if isinstance(pipe, MpmdPipeline):
            raise ValueError("serve_endpoint requires spmd mode")
        pipe.warmup()
        mb, buf = pipe.microbatch, pipe.buf_elems
        in_size = pipe.stages[0].in_spec.size
        ring = HostStagingRing(mb * buf, n_slots=max(4 * pipe.chunk, 16))
        srv = _socket.create_server((host, port))
        address = srv.getsockname()

        #: first error from either thread; a non-empty list aborts the
        #: connection WITHOUT the END frame so the client fails loudly
        #: (never a silently short result stream)
        errors: list[BaseException] = []

        def reader(conn):
            try:
                while True:
                    kind, value = recv_frame(conn)
                    if kind == K_END:
                        ring.close()
                        return
                    if kind != K_TENSOR:
                        raise ConnectionError(
                            f"unexpected frame kind {kind!r} on the "
                            f"endpoint's input stream")
                    x = np.asarray(value, np.float32).reshape(mb, -1)
                    if x.shape[-1] != in_size:
                        raise ValueError(
                            f"sample size {x.shape[-1]} != stage-0 input "
                            f"size {in_size}")
                    if mb == 1:
                        row = x  # native zero-pad to buf_elems
                    else:
                        row = np.zeros((mb, buf), np.float32)
                        row[:, :in_size] = x
                    # a full ring is normal backpressure (client ahead of
                    # the pipeline); a ring still full after the stall
                    # timeout means the pipeline stopped draining — fail
                    # loudly, never silently drop the sample
                    if not ring.push(row, timeout_s=stall_timeout_s):
                        raise RuntimeError(
                            f"staging ring full for {stall_timeout_s:.0f}s "
                            f"— pipeline stalled; sample would be dropped")
            except BaseException as e:  # noqa: BLE001 — any reader death
                errors.append(e)        # must unwedge the serve loop
                ring.close()

        def serve():
            conn, _ = srv.accept()
            conn_lock = threading.Lock()
            threading.Thread(target=reader, args=(conn,), daemon=True,
                             name="defer-endpoint-reader").start()
            pipe.reset()
            try:
                while True:
                    try:
                        got, block = ring.pop_block(pipe.chunk,
                                                    timeout_s=1.0)
                    except TimeoutError:
                        if errors:
                            return  # reader died; abort without END
                        continue
                    if block is None:  # END (or reader error): drain
                        if errors:
                            return  # abort: reset-close, no END frame
                        for o in pipe.flush():
                            with conn_lock:
                                send_frame(conn, np.asarray(o, np.float32),
                                           codec=codec)
                        with conn_lock:
                            send_end(conn)
                        return
                    slab, mask = pipe.push(
                        block.reshape(pipe.chunk, mb, buf), n_real=got,
                        staged=True, raw=True)
                    if slab is None:
                        continue
                    real = np.flatnonzero(mask)
                    if real.size == 0:
                        continue
                    if real.size < len(mask):
                        # trickle traffic: gather real rows on device so
                        # the host transfer never carries bubble padding
                        slab = slab[real]
                    # ONE device->host drain per chunk, then frame out
                    arr = np.asarray(slab, np.float32)
                    out_shape = (mb,) + pipe.out_spec.shape
                    for row in arr:
                        with conn_lock:
                            send_frame(conn, row.reshape(out_shape),
                                       codec=codec)
            except BaseException as e:  # noqa: BLE001 — surfaced on .errors
                errors.append(e)
                raise
            finally:
                conn.close()
                srv.close()

        thread = threading.Thread(target=serve, daemon=True,
                                  name="defer-endpoint")
        thread.errors = errors  # inspectable post-join
        thread.start()
        return address, thread

    def run_defer(self, graph, params, cut_points,
                  input_stream: queue.Queue, output_stream: queue.Queue,
                  *, num_stages=None) -> DeferHandle:
        """Queue-in/queue-out streaming service (the reference's entry point,
        src/dispatcher.py:107).  Returns immediately with a handle; a daemon
        thread drains ``input_stream`` and fills ``output_stream``.  Put
        ``END_OF_STREAM`` (None) on the input queue — or call
        ``handle.stop()`` — to shut down after draining the pipe.
        """
        pipe = self.build(graph, params, cut_points, num_stages)
        stop = threading.Event()
        cfg = self.config

        def serve():
            try:
                _serve_inner()
            except BaseException as e:  # surface errors instead of a silent
                handle.error = e        # dead thread + forever-blocked reader
                output_stream.put(END_OF_STREAM)

        def _dispatch(fn, *a, arm=True, **kw):
            # bracket device work so the watchdog can tell "waiting for
            # input" (fine) from "stuck in a dispatch" (dead pipeline).
            # arm=False exempts dispatches that may legitimately block for
            # an XLA compile (new input shape in MPMD mode) — a compile is
            # not a hang, however long it takes.
            t0 = time.monotonic()
            if arm:
                handle._busy_since = t0
            try:
                out = fn(*a, **kw)
            finally:
                handle._busy_since = None
            handle._dispatches += 1
            handle._max_dispatch_s = max(handle._max_dispatch_s,
                                         time.monotonic() - t0)
            return out

        def _serve_inner():
            if isinstance(pipe, MpmdPipeline):
                if cfg.preflight:
                    # compile-and-run probe before serving traffic (the
                    # reference has no health check at all: a bad partition
                    # only surfaces when a node dies mid-stream, SURVEY.md §5)
                    _dispatch(pipe.run, np.zeros(
                        (1, pipe.microbatch) + pipe.in_spec.shape, np.float32))
                    if handle.error is not None:
                        return
                seen_shapes: set[tuple] = set()
                pipe.reset()
                while not stop.is_set():
                    try:
                        x = input_stream.get(timeout=0.05)
                    except queue.Empty:
                        continue
                    if x is END_OF_STREAM:
                        break
                    xa = np.asarray(x)
                    # a new shape means a fresh per-stage jit compile: don't
                    # let the watchdog mistake compile time for a hang
                    fresh = xa.shape not in seen_shapes
                    seen_shapes.add(xa.shape)
                    # materialize INSIDE the dispatch bracket: push only
                    # enqueues async work, and a wedged device would
                    # otherwise hang np.asarray with the watchdog disarmed
                    outs = _dispatch(
                        lambda: [np.asarray(o, np.float32)
                                 for o in pipe.push(xa[None])],
                        arm=not fresh)
                    if handle.error is not None:
                        return  # watchdog fired mid-dispatch
                    for o in outs:
                        output_stream.put(o)
                if handle.error is not None:
                    return
                outs = _dispatch(lambda: [np.asarray(o, np.float32)
                                          for o in pipe.flush()])
                if handle.error is not None:
                    return
                for o in outs:
                    output_stream.put(o)
                return

            pipe.reset()
            if cfg.preflight:
                # serve the first real input from an already-validated,
                # already-compiled full-chunk program
                _dispatch(pipe.warmup)
                if handle.error is not None:
                    return
            done = False
            while not done and not stop.is_set():
                batch: list[np.ndarray] = []
                try:
                    batch.append(input_stream.get(timeout=0.05))
                except queue.Empty:
                    continue
                if batch[0] is END_OF_STREAM:
                    break
                # opportunistically gather a fuller chunk (the reference's
                # in-flight window); don't stall waiting for stragglers
                while len(batch) < pipe.chunk:
                    try:
                        nxt = input_stream.get(timeout=cfg.gather_timeout_s)
                    except queue.Empty:
                        break
                    if nxt is END_OF_STREAM:
                        done = True
                        break
                    batch.append(nxt)
                n_real = len(batch)
                pad = [np.zeros_like(batch[0])] * (pipe.chunk - n_real)
                block = np.stack(batch + pad)
                # materialize inside the bracket (push is async dispatch;
                # the device block happens at np.asarray)
                outs = _dispatch(
                    lambda: [np.asarray(o, np.float32)
                             for o in pipe.push(block, n_real=n_real)])
                if handle.error is not None:
                    return  # watchdog fired mid-dispatch; sentinel is out
                for o in outs:
                    output_stream.put(o)
            if handle.error is not None:
                return
            outs = _dispatch(lambda: [np.asarray(o, np.float32)
                                      for o in pipe.flush()])
            if handle.error is not None:
                # watchdog fired during the drain dispatch: the sentinel is
                # already on the queue; emitting outputs after it would
                # violate the stream protocol for readers
                return
            for o in outs:
                output_stream.put(o)

        thread = threading.Thread(target=serve, daemon=True,
                                  name="defer-dispatcher")
        handle = DeferHandle(thread, pipe, stop)
        thread.start()

        if cfg.watchdog_s is not None:
            def watch():
                while not stop.is_set() and thread.is_alive():
                    busy = handle._busy_since
                    # threshold self-scales to the slowest dispatch this
                    # deployment has actually completed (compile included):
                    # big-chunk slow-host dispatches raise their own bound
                    # instead of being declared dead at a fixed 60 s
                    wd = max(cfg.watchdog_s,
                             cfg.watchdog_scale * handle._max_dispatch_s)
                    # unarmed until one dispatch completed: the first call
                    # legitimately blocks for the whole jit compile
                    if (handle._dispatches > 0 and busy is not None
                            and time.monotonic() - busy > wd):
                        # a dead device/backend: surface instead of the
                        # reference's forever-hang (SURVEY.md §5 failure row)
                        handle.error = TimeoutError(
                            f"pipeline dispatch made no progress for "
                            f"{wd:.1f}s; deployment declared dead")
                        stop.set()  # serve loop exits; no outputs after the
                        output_stream.put(END_OF_STREAM)  # sentinel below
                        return
                    time.sleep(min(0.25, wd / 4))

            threading.Thread(target=watch, daemon=True,
                             name="defer-watchdog").start()
        return handle
