"""Dispatcher: the user-facing API (capability parity with the reference).

The reference's single entry point is
``DEFER(computeNodes).run_defer(model, partition_layers, input_stream,
output_stream)`` (src/dispatcher.py:107-115): it partitions, ships
sub-models to TCP nodes, then streams a queue of inputs through the chain
and surfaces results on an output queue.  The TPU-native ``Defer`` keeps the
same shape — queue in, queue out, streaming forever until told to stop — but
placement is a device mesh instead of IPs, and all data movement is
ICI/HBM-side (zero CPU-side tensor serialization, per BASELINE.md).
"""

from __future__ import annotations

import collections
import queue
import threading
import time
from typing import Any, Iterable, Iterator

import numpy as np

import jax
import jax.numpy as jnp

from ..graph.ir import LayerGraph
from ..obs import REGISTRY, tracer
from ..obs.events import emit as emit_event
from ..parallel.mesh import pipeline_mesh
from ..partition.partitioner import partition
from ..utils.config import DeferConfig
from .mpmd import MpmdPipeline
from .spmd import SpmdPipeline

#: sentinel a producer puts on the input queue to end the stream
END_OF_STREAM = None


class DeferHandle:
    """Handle to a running streaming deployment (returned by ``run_defer``)."""

    def __init__(self, thread: threading.Thread, pipeline,
                 stop_event: threading.Event):
        self._thread = thread
        self.pipeline = pipeline
        self._stop = stop_event
        #: exception that killed the serve thread, if any
        self.error: BaseException | None = None
        #: monotonic time the serve thread entered its current device
        #: dispatch, or None while idle (used by the watchdog)
        self._busy_since: float | None = None
        #: completed dispatches; the watchdog only arms after the first one
        #: so jit compilation time is never mistaken for a hang
        self._dispatches: int = 0
        #: slowest completed dispatch (seconds) — scales the watchdog
        #: threshold so legitimately slow deployments never false-positive
        self._max_dispatch_s: float = 0.0
        #: serve-thread generation: bumped by the watchdog on recovery so a
        #: stale (wedged, later-unwedged) thread can never emit outputs
        self._gen: int = 0
        #: completed watchdog recoveries (rebuild + replay)
        self.recoveries: int = 0
        #: fed-but-not-yet-emitted real microbatch inputs, seq-stamped —
        #: the same retain-until-ack window the network failover path
        #: uses (``transport/replay.py``), here with "ack" = "output
        #: emitted": a recovery generation replays ``unacked()``.
        #: Assigned by ``run_defer`` once the pipeline's chunk depth
        #: (the window bound) is known.
        self._resubmit = None
        #: next feed seq to stamp / cumulative outputs emitted — the
        #: producer/consumer cursors of the resubmit window
        self._fed: int = 0
        self._emitted: int = 0
        #: True once END_OF_STREAM was consumed from the input queue — a
        #: recovery generation must not wait for a second END (the caller
        #: already sent theirs); it replays, flushes, and exits
        self._end_seen: bool = False

    def stop(self):
        self._stop.set()

    @property
    def healthy(self) -> bool:
        """False once the serve thread died or was declared hung."""
        return self.error is None

    def join(self, timeout: float | None = None):
        """Wait for the serve thread; re-raises any error it died with.

        Raises as soon as ``error`` is set rather than waiting for thread
        exit: when the watchdog declares the deployment dead, the serve
        thread may be permanently wedged inside a device dispatch — exactly
        the case where an unbounded ``Thread.join`` would never return.
        """
        deadline = None if timeout is None else time.monotonic() + timeout
        while self.error is None and self._thread.is_alive():
            step = 0.25
            if deadline is not None:
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                step = min(step, left)
            self._thread.join(step)
        if self.error is not None:
            raise RuntimeError("defer dispatcher thread failed") from self.error

    @property
    def metrics(self):
        return self.pipeline.metrics


class Defer:
    """TPU-native DEFER deployment.

    ``mesh`` plays the role of the reference's ``computeNodes`` IP list
    (src/dispatcher.py:21): it names the devices that will host pipeline
    stages.
    """

    def __init__(self, mesh=None, config: DeferConfig | None = None):
        self.mesh = mesh
        self.config = config or DeferConfig()
        # compiled-engine caches (decoder / score pipelines): repacking
        # weights and re-jitting on every generate()/score() call costs
        # tens of seconds on first dispatch (ADVICE r4).  Values keep the
        # (graph, params) refs alive so the id()-keys can't be recycled.
        # Caching contract: weight updates must produce a NEW params
        # pytree (the JAX-functional norm — optimizer steps do); mutating
        # leaves of a cached dict in place is NOT detected.
        self._decoder_cache: dict[tuple, tuple] = {}
        self._score_cache: dict[tuple, tuple] = {}
        self._CACHE_MAX = 4

    def _cfg_cache_key(self) -> tuple:
        """Config fields that shape a compiled engine — part of every
        engine-cache key so mutating self.config between calls rebuilds."""
        c = self.config
        return (c.microbatch, c.chunk, str(c.compute_dtype),
                str(c.buffer_dtype), c.wire, c.mode, c.master_weights,
                c.data_parallel, c.tensor_parallel)

    def _default_num_stages(self) -> int:
        """Stage count from this deployment's mesh (1 when mesh-less).

        The single lookup both :meth:`generate` and :meth:`score` use — a
        mesh without a stage axis errors clearly instead of silently
        running single-stage."""
        from ..parallel.mesh import STAGE_AXIS
        if self.mesh is None:
            return 1
        if STAGE_AXIS not in self.mesh.shape:
            raise ValueError(
                f"mesh has no {STAGE_AXIS!r} axis; pass num_stages or a "
                "pipeline_mesh")
        return self.mesh.shape[STAGE_AXIS]

    # -- construction ------------------------------------------------------

    def build(self, graph: LayerGraph, params: dict[str, Any],
              cut_points: list[str] | None = None,
              num_stages: int | None = None):
        """Partition + compile; returns the pipeline engine."""
        cfg = self.config
        stages = partition(graph, cut_points, num_stages=num_stages)
        if cfg.mode == "mpmd":
            devices = None
            if self.mesh is not None:
                devices = list(self.mesh.devices.flatten())
            return MpmdPipeline(stages, params, devices=devices,
                                microbatch=cfg.microbatch,
                                compute_dtype=cfg.compute_dtype)
        mesh = self.mesh
        if mesh is None:
            mesh = pipeline_mesh(len(stages), cfg.data_parallel,
                                 cfg.tensor_parallel)
        return SpmdPipeline(
            stages, params, mesh=mesh,
            microbatch=cfg.microbatch, chunk=cfg.chunk,
            buffer_dtype=jnp.dtype(cfg.buffer_dtype),
            compute_dtype=cfg.compute_dtype,
            wire=cfg.wire,
            master_weights=cfg.master_weights,
        )

    def generate(self, graph, params, prompt_ids, max_new_tokens: int,
                 *, num_stages: int | None = None, max_len: int | None = None,
                 kv_cache: str = "buffer", weight_dtype: str | None = None,
                 **sample_kw):
        """Pipelined autoregressive generation (decoder graphs).

        Convenience over :class:`~defer_tpu.runtime.decode.PipelinedDecoder`
        with this deployment's mesh/config: partitions the causal graph's
        blocks over ``num_stages`` (default: the mesh's stage axis, or 1),
        decodes ``max_new_tokens`` past each prompt.  ``sample_kw`` passes
        through (temperature, top_k, seed, eos_id, token_chunk, prefill).
        """
        from .decode import PipelinedDecoder
        if num_stages is None:
            num_stages = self._default_num_stages()
        key = (id(graph), id(params), num_stages, max_len, kv_cache,
               weight_dtype, self._cfg_cache_key())
        hit = self._decoder_cache.get(key)
        if hit is not None and hit[0] is graph and hit[1] is params:
            dec = hit[2]
        else:
            dec = PipelinedDecoder(
                graph, params, num_stages=num_stages, mesh=self.mesh,
                microbatch=self.config.microbatch, max_len=max_len,
                compute_dtype=self.config.compute_dtype, kv_cache=kv_cache,
                weight_dtype=weight_dtype)
            if len(self._decoder_cache) >= self._CACHE_MAX:
                self._decoder_cache.pop(next(iter(self._decoder_cache)))
            self._decoder_cache[key] = (graph, params, dec)
        with tracer().span("defer.generate",
                           {"new_tokens": max_new_tokens}):
            return dec.generate(np.asarray(prompt_ids), max_new_tokens,
                                **sample_kw)

    def logits(self, graph, params, ids, *, cut_points=None,
               num_stages: int | None = None) -> np.ndarray:
        """Full-sequence causal-LM logits [B, T, V] through the pipeline.

        ``ids``: [B, T] ints (B % microbatch == 0).  Routed through a
        LENGTH-BUCKETED pipeline: the graph is re-specced (same ops,
        same params) at the next power-of-two length >= T and jitted per
        bucket, so a 16-token batch under a 256-token graph pays
        16-position attention, not 256 (causal masking makes the results
        bit-identical).  Bucketed pipelines are cached on the instance.
        The verification forward of speculative decoding and the scoring
        path of :meth:`score` both ride this.
        """
        ids = np.asarray(ids)
        if ids.ndim != 2:
            raise ValueError("ids must be [B, T]")
        b, t = ids.shape
        mb = self.config.microbatch
        if b % mb or b == 0:
            raise ValueError(
                f"B={b} must be a non-zero multiple of microbatch={mb}")
        if cut_points is None and num_stages is None:
            num_stages = self._default_num_stages()
        t_model = graph.input_spec.shape[0]
        if t > t_model:
            raise ValueError(
                f"sequence length {t} exceeds the model's {t_model}")
        bucket = max(8, 1 << (max(t, 1) - 1).bit_length())  # next pow2
        bucket = min(bucket, t_model)
        ckey = (id(graph), id(params), bucket, num_stages,
                tuple(cut_points) if cut_points else None,
                self._cfg_cache_key())
        hit = self._score_cache.get(ckey)
        if hit is not None and hit[0] is graph and hit[1] is params:
            pipe = hit[2]
        else:
            g = graph if bucket == t_model else \
                graph.with_input_shape((bucket,))
            pipe = self.build(g, params, cut_points, num_stages)
            if len(self._score_cache) >= self._CACHE_MAX:
                self._score_cache.pop(next(iter(self._score_cache)))
            self._score_cache[ckey] = (graph, params, pipe)
        # causal attention: right-padding cannot influence positions < t,
        # so pad to the bucket length and read the real prefix
        padded = np.zeros((b, bucket), ids.dtype)
        padded[:, :t] = ids
        out = pipe.run(
            padded.reshape(b // mb, mb, bucket).astype(np.float32))
        return out.reshape(b, bucket, -1)[:, :t]

    def score(self, graph, params, ids, *, cut_points=None,
              num_stages: int | None = None):
        """Per-sequence log-likelihood of token ids under a causal LM.

        ``ids``: [B, T] ints (B % microbatch == 0).  Runs the causal
        graph through the (length-bucketed, cached) inference pipeline
        and sums next-token log-probabilities.  Returns
        ``(logprob [B], perplexity [B])`` — the evaluation-side companion
        of :meth:`generate`.
        """
        ids = np.asarray(ids)
        if ids.ndim != 2:
            raise ValueError("ids must be [B, T]")
        b, t = ids.shape
        logits = self.logits(graph, params, ids, cut_points=cut_points,
                             num_stages=num_stages)
        logp = jax.nn.log_softmax(jnp.asarray(logits, jnp.float32), axis=-1)
        tgt = jnp.asarray(ids[:, 1:], jnp.int32)
        pick = jnp.take_along_axis(logp[:, :-1], tgt[..., None], -1)[..., 0]
        total = np.asarray(pick.sum(axis=-1))
        ppl = np.exp(-total / (t - 1)) if t > 1 else np.ones(b)
        return total, ppl

    # -- health ------------------------------------------------------------

    def health_check(self, graph, params, cut_points=None, num_stages=None):
        """Compile-and-run probe of a deployment before serving traffic.

        Builds the pipeline, pushes one all-bubble chunk through the
        compiled program, and reports per-deployment status — the "health
        check on stage program compilation" the reference lacks entirely
        (SURVEY.md §5: a bad partition there only surfaces when a node
        crashes mid-stream).  Raises nothing: failures come back in the
        report so callers can decide.
        """
        report: dict[str, Any] = {"ok": False, "stages": None,
                                  "mesh": None, "error": None}
        try:
            pipe = self.build(graph, params, cut_points, num_stages)
            report["stages"] = len(pipe.stages)
            if getattr(pipe, "mesh", None) is not None:
                report["mesh"] = dict(pipe.mesh.shape)
            if isinstance(pipe, MpmdPipeline):
                x = np.zeros((1, pipe.microbatch) + pipe.in_spec.shape,
                             np.float32)
                pipe.run(x)
            else:
                # full-chunk bubble probe: the compiled artifact exercised
                # here is the exact [chunk, ...] program that will serve
                # traffic (a [1, ...] probe would compile a different
                # program and miss chunk-shape-specific failures)
                pipe.warmup()
            report["ok"] = True
        except Exception as e:  # noqa: BLE001 — report, don't raise
            report["error"] = e
        return report

    # -- batch API ---------------------------------------------------------

    def run(self, graph, params, inputs, cut_points=None, num_stages=None):
        """One-shot batched inference over the pipeline."""
        pipe = self.build(graph, params, cut_points, num_stages)
        return pipe.run(inputs)

    # -- streaming APIs ----------------------------------------------------

    def stream(self, graph, params, inputs: Iterable[np.ndarray],
               cut_points=None, num_stages=None) -> Iterator[np.ndarray]:
        """Generator streaming: yields one output per input microbatch."""
        pipe = self.build(graph, params, cut_points, num_stages)
        if isinstance(pipe, MpmdPipeline):
            for x in inputs:
                yield pipe.run(x[None])[0]
            return
        pipe.reset()
        batch: list[np.ndarray] = []
        for x in inputs:
            batch.append(x)
            if len(batch) == pipe.chunk:
                yield from pipe.push(np.stack(batch))
                batch.clear()
        if batch:
            pad = [np.zeros_like(batch[0])] * (pipe.chunk - len(batch))
            yield from pipe.push(np.stack(batch + pad), n_real=len(batch))
        yield from pipe.flush()

    def serve_endpoint(self, graph, params, cut_points=None, *,
                       num_stages=None, host: str = "127.0.0.1",
                       port: int = 0, codec: str = "raw",
                       stall_timeout_s: float = 120.0,
                       max_clients: int = 1):
        """Network front door: accept framed tensors, stream them through
        the pipeline via the native staging ring, reply in order.

        This is the reference dispatcher's whole socket data plane
        (src/dispatcher.py:85-105) as one endpoint, grown past its
        ``listen(1)`` (reference src/node.py:84-85): up to ``max_clients``
        clients — concurrent or successive (reconnects after a client
        death) — share ONE compiled pipeline.  Each client's reader thread
        stages samples into the bounded native ring
        (``transport/staging.py``) under a per-client in-flight window (so
        one greedy client cannot starve the rest); sample provenance rides
        a FIFO owners queue that mirrors ring order, and the serve loop
        routes each emitted row back to its owner's connection — every
        client sees exactly its own results, in its own send order.  A
        client that dies mid-stream is discarded (its in-flight rows are
        dropped on emergence) without disturbing the others.

        Returns ``(server_address, thread)``; the thread exits once
        ``max_clients`` connections have finished (END-drained and echoed,
        or died) — or when ``thread.stop()`` is called (an operator
        shutdown: stops accepting, drains in-flight rows, cuts any
        still-connected clients without an END so they fail loudly).
        """
        import socket as _socket

        from ..transport.framed import (K_END, K_TENSOR, configure_socket,
                                        recv_frame, send_end, send_frame)
        from ..transport.staging import HostStagingRing

        pipe = self.build(graph, params, cut_points, num_stages)
        if isinstance(pipe, MpmdPipeline):
            raise ValueError("serve_endpoint requires spmd mode")
        pipe.warmup()
        mb, buf = pipe.microbatch, pipe.buf_elems
        in_size = pipe.stages[0].in_spec.size
        n_slots = max(4 * pipe.chunk, 16)
        ring = HostStagingRing(mb * buf, n_slots=n_slots)
        srv = _socket.create_server((host, port))
        address = srv.getsockname()
        ep_in = REGISTRY.counter("endpoint.samples_in")
        ep_out = REGISTRY.counter("endpoint.samples_out")

        #: endpoint-fatal errors (pipeline death) PLUS per-client aborts;
        #: a client whose stream errors is cut WITHOUT the END frame so it
        #: fails loudly (never a silently short result stream)
        errors: list[BaseException] = []

        class _Client:
            __slots__ = ("conn", "lock", "state", "alive", "draining",
                         "outstanding", "window")

            def __init__(self, conn):
                self.conn = conn
                self.lock = threading.Lock()    # serializes writes
                self.state = threading.Lock()   # guards the fields below
                self.alive = True
                self.draining = False
                self.outstanding = 0
                # fair-share cap on ring slots one client may occupy
                self.window = threading.Semaphore(
                    max(pipe.chunk, n_slots // (2 * max_clients)))

        owners: collections.deque[_Client] = collections.deque()
        push_lock = threading.Lock()  # makes (ring.push, owners.append) atomic
        finished = threading.Semaphore(0)  # one release per finished client
        clients: list[_Client] = []  # every accepted client, for teardown
        stop_ev = threading.Event()  # operator shutdown (thread.stop())

        def _finish(client: _Client, *, send_eos: bool):
            """Exactly-once client teardown; END echo only on clean drain."""
            with client.state:
                if not client.alive:
                    return
                client.alive = False
            try:
                if send_eos:
                    with client.lock:
                        send_end(client.conn)
            except OSError:
                pass
            client.conn.close()
            finished.release()

        def _maybe_drained(client: _Client):
            with client.state:
                done = (client.draining and client.outstanding == 0
                        and client.alive)
            if done:
                _finish(client, send_eos=True)

        def reader(client: _Client):
            conn = client.conn
            try:
                while True:
                    kind, value = recv_frame(conn)
                    if kind == K_END:
                        with client.state:
                            client.draining = True
                        _maybe_drained(client)
                        return
                    if kind != K_TENSOR:
                        raise ConnectionError(
                            f"unexpected frame kind {kind!r} on the "
                            f"endpoint's input stream")
                    x = np.asarray(value, np.float32).reshape(mb, -1)
                    if x.shape[-1] != in_size:
                        raise ValueError(
                            f"sample size {x.shape[-1]} != stage-0 input "
                            f"size {in_size}")
                    if mb == 1:
                        row = x  # native zero-pad to buf_elems
                    else:
                        row = np.zeros((mb, buf), np.float32)
                        row[:, :in_size] = x
                    if not client.window.acquire(timeout=stall_timeout_s):
                        raise RuntimeError(
                            f"client window full for {stall_timeout_s:.0f}s "
                            f"— pipeline stalled; sample would be dropped")
                    # a full ring is normal backpressure (clients ahead of
                    # the pipeline); a ring still full after the stall
                    # timeout means the pipeline stopped draining — fail
                    # loudly, never silently drop the sample.  The owner
                    # entry is registered BEFORE the push (a pushed sample
                    # is instantly poppable — its owner must already be
                    # queued) and retracted on failure; push_lock holds are
                    # kept short (50 ms slices) so one backpressured client
                    # never serializes the others for the whole stall
                    # budget.
                    deadline = time.monotonic() + stall_timeout_s
                    while True:
                        with push_lock:
                            owners.append(client)
                            with client.state:
                                client.outstanding += 1
                            ok = ring.push(row, timeout_s=0.05)
                            if not ok:
                                owners.pop()  # ours: appends are lock-held
                                with client.state:
                                    client.outstanding -= 1
                        if ok:
                            ep_in.n += 1
                            break
                        if time.monotonic() > deadline:
                            raise RuntimeError(
                                f"staging ring full for "
                                f"{stall_timeout_s:.0f}s — pipeline "
                                f"stalled; sample would be dropped")
            except BaseException as e:  # noqa: BLE001 — client-fatal
                errors.append(e)
                _finish(client, send_eos=False)

        def acceptor():
            for _ in range(max_clients):
                try:
                    conn, _ = srv.accept()
                except OSError:
                    return  # endpoint shut down
                configure_socket(conn)
                client = _Client(conn)
                clients.append(client)
                threading.Thread(target=reader, args=(client,),
                                 daemon=True,
                                 name="defer-endpoint-reader").start()

        def _deliver(row: np.ndarray, out_shape):
            client = owners.popleft()
            with client.state:
                client.outstanding -= 1
                alive = client.alive
            client.window.release()
            if alive:
                try:
                    with client.lock:
                        send_frame(client.conn, row.reshape(out_shape),
                                   codec=codec)
                except OSError as e:
                    errors.append(e)
                    _finish(client, send_eos=False)
                else:
                    ep_out.n += 1
                    _maybe_drained(client)

        def serve():
            threading.Thread(target=acceptor, daemon=True,
                             name="defer-endpoint-accept").start()
            pipe.reset()
            out_shape = (mb,) + pipe.out_spec.shape
            done_clients = 0
            try:
                while done_clients < max_clients or owners:
                    if stop_ev.is_set() and not owners:
                        return  # operator stop: in-flight rows drained
                    while finished.acquire(blocking=False):
                        done_clients += 1
                    try:
                        got, block = ring.pop_block(pipe.chunk,
                                                    timeout_s=0.25)
                    except TimeoutError:
                        if not owners:
                            continue
                        # undelivered rows are inside the pipe and no new
                        # traffic is arriving: crank it with the cached
                        # device-resident bubble block (flush()'s recipe)
                        got, block = 0, pipe._bubble_block()
                    if block is None:
                        continue  # ring closed (teardown)
                    xs = block if got == 0 else \
                        block.reshape(pipe.chunk, mb, buf)
                    slab, mask = pipe.push(xs, n_real=got,
                                           staged=got > 0, raw=True)
                    if slab is None:
                        continue
                    real = np.flatnonzero(mask)
                    if real.size == 0:
                        continue
                    if real.size < len(mask):
                        # trickle traffic: gather real rows on device so
                        # the host transfer never carries bubble padding
                        slab = slab[real]
                    # ONE device->host drain per chunk, then frame out
                    arr = np.asarray(slab, np.float32)
                    for row in arr:
                        _deliver(row, out_shape)
            except BaseException as e:  # noqa: BLE001 — endpoint-fatal
                errors.append(e)
                raise
            finally:
                ring.close()
                srv.close()
                # endpoint-fatal exit: cut every live client WITHOUT an END
                # echo so remote peers fail loudly instead of blocking in
                # recv forever (normal exits find no one alive here)
                for c in clients:
                    _finish(c, send_eos=False)

        thread = threading.Thread(target=serve, daemon=True,
                                  name="defer-endpoint")
        thread.errors = errors  # inspectable post-join
        # live redeploy: swap weights under the serving pipeline with no
        # recompile and no client disruption (attribute swap is atomic;
        # the chunk in flight finishes under the weights it started with)
        thread.reweight = pipe.reweight

        def _stop():
            stop_ev.set()
            srv.close()  # unblocks the acceptor; serve loop exits after
            #              draining whatever rows are already in flight

        thread.stop = _stop
        thread.start()
        return address, thread

    def run_defer(self, graph, params, cut_points,
                  input_stream: queue.Queue, output_stream: queue.Queue,
                  *, num_stages=None) -> DeferHandle:
        """Queue-in/queue-out streaming service (the reference's entry point,
        src/dispatcher.py:107).  Returns immediately with a handle; a daemon
        thread drains ``input_stream`` and fills ``output_stream``.  Put
        ``END_OF_STREAM`` (None) on the input queue — or call
        ``handle.stop()`` — to shut down after draining the pipe.
        """
        from ..transport.replay import ReplayBuffer

        pipe = self.build(graph, params, cut_points, num_stages)
        stop = threading.Event()
        cfg = self.config
        disp_count = REGISTRY.counter("dispatcher.dispatches")
        disp_hist = REGISTRY.histogram("dispatcher.dispatch_s")
        # the resubmit window's bound: everything a pipeline can hold
        # fed-but-unemitted, with slack for the gather in progress (the
        # MPMD path never logs — its capacity is a placeholder)
        log_cap = 1 if isinstance(pipe, MpmdPipeline) \
            else 2 * (pipe.chunk + pipe.num_stages + 1)

        def _dispatch(gen, fn, *a, arm=True, **kw):
            # bracket device work so the watchdog can tell "waiting for
            # input" (fine) from "stuck in a dispatch" (dead pipeline).
            # arm=False exempts dispatches that may legitimately block for
            # an XLA compile (new input shape in MPMD mode) — a compile is
            # not a hang, however long it takes.  All handle bookkeeping is
            # generation-guarded: a wedged thread that unwedges after a
            # recovery must not clobber the live generation's markers.
            t0 = time.monotonic()
            tp0 = time.perf_counter()
            if arm and handle._gen == gen:
                handle._busy_since = t0
            try:
                out = fn(*a, **kw)
            finally:
                if handle._gen == gen:
                    handle._busy_since = None
            if handle._gen == gen:
                handle._dispatches += 1
                handle._max_dispatch_s = max(handle._max_dispatch_s,
                                             time.monotonic() - t0)
            dt = time.monotonic() - t0
            disp_count.n += 1
            disp_hist.record(dt)
            tr = tracer()
            if tr.enabled:
                tr.record("dispatcher.dispatch", tp0, dt, {"gen": gen})
            return out

        def _serve_inner(pipe, replay, gen):
            def live() -> bool:
                return handle._gen == gen and handle.error is None

            if isinstance(pipe, MpmdPipeline):
                if cfg.preflight:
                    # compile-and-run probe before serving traffic (the
                    # reference has no health check at all: a bad partition
                    # only surfaces when a node dies mid-stream, SURVEY.md §5)
                    _dispatch(gen, pipe.run, np.zeros(
                        (1, pipe.microbatch) + pipe.in_spec.shape, np.float32))
                    if not live():
                        return
                seen_shapes: set[tuple] = set()
                pipe.reset()
                while not stop.is_set() and live():
                    try:
                        x = input_stream.get(timeout=0.05)
                    except queue.Empty:
                        continue
                    if x is END_OF_STREAM:
                        break
                    xa = np.asarray(x)
                    # a new shape means a fresh per-stage jit compile: don't
                    # let the watchdog mistake compile time for a hang
                    fresh = xa.shape not in seen_shapes
                    seen_shapes.add(xa.shape)
                    # materialize INSIDE the dispatch bracket: push only
                    # enqueues async work, and a wedged device would
                    # otherwise hang np.asarray with the watchdog disarmed
                    outs = _dispatch(
                        gen,
                        lambda: [np.asarray(o, np.float32)
                                 for o in pipe.push(xa[None])],
                        arm=not fresh)
                    if not live():
                        return  # watchdog fired mid-dispatch
                    for o in outs:
                        output_stream.put(o)
                if not live():
                    return
                outs = _dispatch(gen, lambda: [np.asarray(o, np.float32)
                                               for o in pipe.flush()])
                if not live():
                    return
                for o in outs:
                    output_stream.put(o)
                return

            # ---- SPMD path: resubmit log + replay-aware input feed ----
            log = handle._resubmit
            pending: collections.deque = collections.deque(replay)

            def next_input(timeout: float):
                if pending:
                    return pending.popleft()
                if handle._end_seen:
                    # the caller's END was consumed by a previous (wedged)
                    # generation; never wait for a second one
                    raise queue.Empty
                return input_stream.get(timeout=timeout)

            pipe.reset()
            if cfg.preflight:
                # serve the first real input from an already-validated,
                # already-compiled full-chunk program.  arm=False: on a
                # recovery generation _dispatches is already > 0 and this
                # (compile) dispatch would otherwise re-trip the watchdog
                _dispatch(gen, pipe.warmup, arm=False)
                if not live():
                    return
            done = False
            while not done and not stop.is_set() and live():
                if handle._end_seen and not pending:
                    break  # recovery after END: replay done, go flush
                batch: list[np.ndarray] = []
                try:
                    batch.append(next_input(0.05))
                except queue.Empty:
                    if handle._end_seen:
                        break
                    continue
                if batch[0] is END_OF_STREAM:
                    handle._end_seen = True
                    break
                # opportunistically gather a fuller chunk (the reference's
                # in-flight window); don't stall waiting for stragglers
                while len(batch) < pipe.chunk:
                    try:
                        nxt = next_input(cfg.gather_timeout_s)
                    except queue.Empty:
                        break
                    if nxt is END_OF_STREAM:
                        handle._end_seen = True
                        done = True
                        break
                    batch.append(nxt)
                n_real = len(batch)
                pad = [np.zeros_like(batch[0])] * (pipe.chunk - n_real)
                block = np.stack(batch + pad)
                # record the fed microbatches BEFORE dispatch: if the
                # dispatch wedges, the recovery generation replays exactly
                # these (plus everything older still in the pipe)
                for x in batch:
                    if log.depth() >= log.capacity:
                        # can't happen: acks track emits.  Raise instead
                        # of letting retain() block on the bug.
                        raise RuntimeError(
                            f"resubmit log overflow ({log.depth()} >= "
                            f"{log.capacity})")
                    log.retain(handle._fed, x)
                    handle._fed += 1
                # materialize inside the bracket (push is async dispatch;
                # the device block happens at np.asarray)
                outs = _dispatch(
                    gen,
                    lambda: [np.asarray(o, np.float32)
                             for o in pipe.push(block, n_real=n_real)])
                if not live():
                    return  # watchdog fired mid-dispatch; sentinel is out
                for o in outs:
                    # emitted: no longer replayable (cumulative ack, the
                    # in-process twin of the fan-in's replay_ack)
                    handle._emitted += 1
                    log.ack(handle._emitted)
                    output_stream.put(o)
            if not live():
                return
            outs = _dispatch(gen, lambda: [np.asarray(o, np.float32)
                                           for o in pipe.flush()])
            if not live():
                # watchdog fired during the drain dispatch: the sentinel is
                # already on the queue; emitting outputs after it would
                # violate the stream protocol for readers
                return
            for o in outs:
                handle._emitted += 1
                log.ack(handle._emitted)
                output_stream.put(o)

        def start_generation(pipe, replay, gen):
            def serve():
                try:
                    _serve_inner(pipe, replay, gen)
                except BaseException as e:  # surface errors instead of a
                    if handle._gen == gen:  # silent dead thread + forever-
                        handle.error = e    # blocked reader
                        output_stream.put(END_OF_STREAM)

            t = threading.Thread(target=serve, daemon=True,
                                 name=f"defer-dispatcher-g{gen}")
            handle._thread = t
            handle.pipeline = pipe
            t.start()

        handle = DeferHandle(None, pipe, stop)
        handle._resubmit = ReplayBuffer(log_cap,
                                        gauge="dispatcher.replay_depth")
        start_generation(pipe, [], 0)

        if cfg.watchdog_s is not None:
            def watch():
                while not stop.is_set() and handle._thread.is_alive():
                    busy = handle._busy_since
                    # threshold self-scales to the slowest dispatch this
                    # deployment has actually completed (compile included):
                    # big-chunk slow-host dispatches raise their own bound
                    # instead of being declared dead at a fixed 60 s
                    wd = max(cfg.watchdog_s,
                             cfg.watchdog_scale * handle._max_dispatch_s)
                    # unarmed until one dispatch completed: the first call
                    # legitimately blocks for the whole jit compile
                    if (handle._dispatches > 0 and busy is not None
                            and time.monotonic() - busy > wd):
                        if (handle.recoveries < cfg.max_recoveries
                                and not isinstance(handle.pipeline,
                                                   MpmdPipeline)):
                            # RECOVER (SURVEY §5 upgraded from "surface the
                            # hang" to "survive it"): abandon the wedged
                            # generation, rebuild the pipeline fresh, and
                            # replay the fed-but-unemitted microbatches
                            handle.recoveries += 1
                            handle._gen += 1
                            handle._busy_since = None
                            emit_event("watchdog", action="recover",
                                       gen=handle._gen,
                                       stalled_s=round(
                                           time.monotonic() - busy, 3))
                            t_rec = time.perf_counter()
                            # the unacked window IS the replay set; the
                            # recovery generation re-feeds (re-retains)
                            # it through the normal path, so it gets a
                            # fresh window and a fresh seq space
                            replay = [v for _, v
                                      in handle._resubmit.unacked()]
                            handle._resubmit = ReplayBuffer(
                                log_cap, gauge="dispatcher.replay_depth")
                            handle._fed = handle._emitted = 0
                            try:
                                new_pipe = self.build(graph, params,
                                                      cut_points, num_stages)
                            except BaseException as e:  # noqa: BLE001
                                handle.error = e
                                stop.set()
                                output_stream.put(END_OF_STREAM)
                                return
                            start_generation(new_pipe, replay, handle._gen)
                            # same event the network heal emits: one
                            # vocabulary for "a hop died and its unacked
                            # window was replayed", wherever the hop is
                            emit_event(
                                "failover", hop="dispatcher",
                                chan=handle._gen, addr="in-process",
                                replayed=len(replay),
                                recovery_ms=round(
                                    (time.perf_counter() - t_rec) * 1e3,
                                    3))
                            continue
                        # out of recoveries (or MPMD): a dead device/backend
                        # surfaces instead of the reference's forever-hang
                        # (SURVEY.md §5 failure row)
                        emit_event("watchdog", action="dead",
                                   gen=handle._gen,
                                   stalled_s=round(
                                       time.monotonic() - busy, 3))
                        # a declared-dead deployment is a postmortem
                        # trigger: assemble the bundle from whatever
                        # journals exist (no-op unless journaling)
                        from ..obs.postmortem import maybe_autopsy
                        maybe_autopsy("watchdog: deployment declared "
                                      "dead")
                        handle.error = TimeoutError(
                            f"pipeline dispatch made no progress for "
                            f"{wd:.1f}s; deployment declared dead")
                        stop.set()  # serve loop exits; no outputs after the
                        output_stream.put(END_OF_STREAM)  # sentinel below
                        return
                    time.sleep(min(0.25, wd / 4))

            threading.Thread(target=watch, daemon=True,
                             name="defer-watchdog").start()
        return handle
