"""Dispatcher: the user-facing API (capability parity with the reference).

The reference's single entry point is
``DEFER(computeNodes).run_defer(model, partition_layers, input_stream,
output_stream)`` (src/dispatcher.py:107-115): it partitions, ships
sub-models to TCP nodes, then streams a queue of inputs through the chain
and surfaces results on an output queue.  The TPU-native ``Defer`` keeps the
same shape — queue in, queue out, streaming forever until told to stop — but
placement is a device mesh instead of IPs, and all data movement is
ICI/HBM-side (zero CPU-side tensor serialization, per BASELINE.md).
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Iterable, Iterator

import numpy as np

import jax.numpy as jnp

from ..graph.ir import LayerGraph
from ..parallel.mesh import pipeline_mesh
from ..partition.partitioner import partition
from ..utils.config import DeferConfig
from .mpmd import MpmdPipeline
from .spmd import SpmdPipeline

#: sentinel a producer puts on the input queue to end the stream
END_OF_STREAM = None


class DeferHandle:
    """Handle to a running streaming deployment (returned by ``run_defer``)."""

    def __init__(self, thread: threading.Thread, pipeline,
                 stop_event: threading.Event):
        self._thread = thread
        self.pipeline = pipeline
        self._stop = stop_event
        #: exception that killed the serve thread, if any
        self.error: BaseException | None = None

    def stop(self):
        self._stop.set()

    def join(self, timeout: float | None = None):
        """Wait for the serve thread; re-raises any error it died with."""
        self._thread.join(timeout)
        if self.error is not None:
            raise RuntimeError("defer dispatcher thread failed") from self.error

    @property
    def metrics(self):
        return self.pipeline.metrics


class Defer:
    """TPU-native DEFER deployment.

    ``mesh`` plays the role of the reference's ``computeNodes`` IP list
    (src/dispatcher.py:21): it names the devices that will host pipeline
    stages.
    """

    def __init__(self, mesh=None, config: DeferConfig | None = None):
        self.mesh = mesh
        self.config = config or DeferConfig()

    # -- construction ------------------------------------------------------

    def build(self, graph: LayerGraph, params: dict[str, Any],
              cut_points: list[str] | None = None,
              num_stages: int | None = None):
        """Partition + compile; returns the pipeline engine."""
        cfg = self.config
        stages = partition(graph, cut_points, num_stages=num_stages)
        if cfg.mode == "mpmd":
            devices = None
            if self.mesh is not None:
                devices = list(self.mesh.devices.flatten())
            return MpmdPipeline(stages, params, devices=devices,
                                microbatch=cfg.microbatch,
                                compute_dtype=cfg.compute_dtype)
        mesh = self.mesh
        if mesh is None:
            mesh = pipeline_mesh(len(stages), cfg.data_parallel)
        return SpmdPipeline(
            stages, params, mesh=mesh,
            microbatch=cfg.microbatch, chunk=cfg.chunk,
            buffer_dtype=jnp.dtype(cfg.buffer_dtype),
            compute_dtype=cfg.compute_dtype,
        )

    # -- batch API ---------------------------------------------------------

    def run(self, graph, params, inputs, cut_points=None, num_stages=None):
        """One-shot batched inference over the pipeline."""
        pipe = self.build(graph, params, cut_points, num_stages)
        return pipe.run(inputs)

    # -- streaming APIs ----------------------------------------------------

    def stream(self, graph, params, inputs: Iterable[np.ndarray],
               cut_points=None, num_stages=None) -> Iterator[np.ndarray]:
        """Generator streaming: yields one output per input microbatch."""
        pipe = self.build(graph, params, cut_points, num_stages)
        if isinstance(pipe, MpmdPipeline):
            for x in inputs:
                yield pipe.run(x[None])[0]
            return
        pipe.reset()
        batch: list[np.ndarray] = []
        for x in inputs:
            batch.append(x)
            if len(batch) == pipe.chunk:
                yield from pipe.push(np.stack(batch))
                batch.clear()
        if batch:
            pad = [np.zeros_like(batch[0])] * (pipe.chunk - len(batch))
            yield from pipe.push(np.stack(batch + pad), n_real=len(batch))
        yield from pipe.flush()

    def run_defer(self, graph, params, cut_points,
                  input_stream: queue.Queue, output_stream: queue.Queue,
                  *, num_stages=None) -> DeferHandle:
        """Queue-in/queue-out streaming service (the reference's entry point,
        src/dispatcher.py:107).  Returns immediately with a handle; a daemon
        thread drains ``input_stream`` and fills ``output_stream``.  Put
        ``END_OF_STREAM`` (None) on the input queue — or call
        ``handle.stop()`` — to shut down after draining the pipe.
        """
        pipe = self.build(graph, params, cut_points, num_stages)
        stop = threading.Event()
        cfg = self.config

        def serve():
            try:
                _serve_inner()
            except BaseException as e:  # surface errors instead of a silent
                handle.error = e        # dead thread + forever-blocked reader
                output_stream.put(END_OF_STREAM)

        def _serve_inner():
            if isinstance(pipe, MpmdPipeline):
                while not stop.is_set():
                    try:
                        x = input_stream.get(timeout=0.05)
                    except queue.Empty:
                        continue
                    if x is END_OF_STREAM:
                        break
                    output_stream.put(pipe.run(np.asarray(x)[None])[0])
                return

            pipe.reset()
            done = False
            while not done and not stop.is_set():
                batch: list[np.ndarray] = []
                try:
                    batch.append(input_stream.get(timeout=0.05))
                except queue.Empty:
                    continue
                if batch[0] is END_OF_STREAM:
                    break
                # opportunistically gather a fuller chunk (the reference's
                # in-flight window); don't stall waiting for stragglers
                while len(batch) < pipe.chunk:
                    try:
                        nxt = input_stream.get(timeout=cfg.gather_timeout_s)
                    except queue.Empty:
                        break
                    if nxt is END_OF_STREAM:
                        done = True
                        break
                    batch.append(nxt)
                n_real = len(batch)
                pad = [np.zeros_like(batch[0])] * (pipe.chunk - n_real)
                outs = pipe.push(np.stack(batch + pad), n_real=n_real)
                for o in outs:
                    output_stream.put(np.asarray(o, np.float32))
            for o in pipe.flush():
                output_stream.put(np.asarray(o, np.float32))

        thread = threading.Thread(target=serve, daemon=True,
                                  name="defer-dispatcher")
        handle = DeferHandle(thread, pipe, stop)
        thread.start()
        return handle
