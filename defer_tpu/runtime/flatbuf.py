"""Flat per-stage weight buffers: the shared pack/unpack scheme.

Both pipeline runtimes (inference ``runtime/spmd.py``, decoding
``runtime/decode.py``) ship each stage's parameter pytree as one flat row of
a ``[num_stages, Pmax]`` array sharded over the ``stage`` mesh axis — the
TPU-native replacement for the reference's runtime weight shipping
(reference src/dispatcher.py:67-80): placement is a sharding annotation, not
a socket protocol.  This module is the single definition of the row layout
so both engines (and any future one) pack and unpack identically.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import numpy as np

from jax import lax
import jax


#: per-leaf layout record: (offset, size, shape, dtype)
LeafMeta = tuple[int, int, tuple[int, ...], Any]


def leaf_meta(leaves: Sequence[np.ndarray]) -> list[LeafMeta]:
    """Offsets/shapes/dtypes of ``leaves`` laid out back-to-back."""
    meta, off = [], 0
    for leaf in leaves:
        leaf = np.asarray(leaf)
        meta.append((off, leaf.size, leaf.shape, leaf.dtype))
        off += leaf.size
    return meta


def check_layout(leaves: Sequence[np.ndarray], treedef,
                 want_meta: Sequence[LeafMeta], want_treedef,
                 what: str) -> None:
    """Validate PRE-cast leaves + treedef against a deployed row layout.

    The one rule both engines' ``reweight`` paths share: the compiled
    programs unflatten with the init-recorded treedef/shapes, and a
    silent dtype change would blind-cast values — so structure, shapes,
    AND original dtypes must match or we raise before touching the
    deployed buffer.
    """
    if treedef != want_treedef:
        raise ValueError(
            f"{what}: param tree structure differs from the deployed one")
    want = [(m[2], np.dtype(m[3])) for m in want_meta]
    got = [(np.shape(l), np.asarray(l).dtype) for l in leaves]
    if want != got:
        raise ValueError(f"{what}: leaves {got} != deployed {want}")


def pack_leaves(leaves: Sequence[np.ndarray], wire_dtype,
                cast_fn: Callable[[np.ndarray], np.ndarray] | None = None,
                ) -> np.ndarray:
    """One flat row: each leaf cast (``cast_fn`` or plain astype), raveled,
    concatenated in order."""
    if not leaves:
        return np.zeros((0,), wire_dtype)
    cast = cast_fn if cast_fn is not None \
        else (lambda a: np.asarray(a).astype(wire_dtype))
    return np.concatenate([cast(np.asarray(l)).ravel() for l in leaves])


def stack_rows(rows: Sequence[np.ndarray], wire_dtype) -> np.ndarray:
    """[N, Pmax] buffer: rows right-padded with zeros to the longest."""
    pmax = max(max((r.size for r in rows), default=1), 1)
    buf = np.zeros((len(rows), pmax), wire_dtype)
    for i, r in enumerate(rows):
        buf[i, : r.size] = r
    return buf


#: per-leaf scale slot within the scale row: (offset, size)
ScaleMeta = tuple[int, int]


def quantize_leaves(leaves: Sequence[np.ndarray]
                    ) -> tuple[np.ndarray, np.ndarray, list[ScaleMeta]]:
    """Symmetric int8 quantization with channel-wise (last-axis) scales.

    Returns ``(q_row int8, scale_row f32, smeta)`` — the W8A16 leaf
    layout: each leaf's int8 values at the SAME element offsets
    ``leaf_meta`` records, plus a parallel f32 scale row.  1-D leaves
    (LN scales, biases) get per-element scales — exactly invertible.
    """
    qs, ss, smeta, soff = [], [], [], 0
    for leaf in leaves:
        a = np.asarray(leaf, np.float32)
        red = tuple(range(max(a.ndim - 1, 0)))  # all axes but the last
        scale = np.maximum(np.abs(a).max(axis=red) / 127.0, 1e-12) \
            if a.ndim else np.maximum(np.abs(a) / 127.0, 1e-12)
        q = np.clip(np.rint(a / scale), -127, 127).astype(np.int8)
        qs.append(q.ravel())
        ss.append(np.asarray(scale, np.float32).ravel())
        smeta.append((soff, ss[-1].size))
        soff += ss[-1].size
    q_row = np.concatenate(qs) if qs else np.zeros((0,), np.int8)
    s_row = np.concatenate(ss) if ss else np.zeros((0,), np.float32)
    return q_row, s_row, smeta


def unpack_quant_leaves(q_local: jax.Array, s_local: jax.Array,
                        meta: Sequence[LeafMeta],
                        smeta: Sequence[ScaleMeta], treedef, dtype):
    """Rebuild a pytree from its int8 row + scale row (inside jit).

    The dequant multiply stays next to the consuming op so XLA fuses it;
    HBM traffic is the int8 bytes plus the (negligible) scales.
    """
    leaves = []
    for (off, size, shape, _dt), (soff, ssize) in zip(meta, smeta):
        q = lax.slice(q_local, (off,), (off + size,)).reshape(shape)
        sc = lax.slice(s_local, (soff,), (soff + ssize,))
        sc = sc.reshape(shape[-1:] if shape else ())
        leaves.append(q.astype(dtype) * sc.astype(dtype))
    return jax.tree.unflatten(treedef, leaves)


def unpack_leaves(w_local: jax.Array, meta: Sequence[LeafMeta], treedef,
                  leaf_dtype: Callable[[Any], Any] | None = None):
    """Rebuild the stage pytree from its flat row (inside jit).

    ``leaf_dtype`` maps each stored dtype to the dtype the consumer wants
    (e.g. the compute-dtype cast of ``runtime/spmd.py``); ``None`` keeps
    the buffer dtype as-is.
    """
    leaves = []
    for off, size, shape, dtype in meta:
        leaf = lax.slice(w_local, (off,), (off + size,)).reshape(shape)
        if leaf_dtype is not None:
            leaf = leaf.astype(leaf_dtype(dtype))
        leaves.append(leaf)
    return jax.tree.unflatten(treedef, leaves)
