"""MPMD relay pipeline — the correctness oracle / debug execution mode.

This is the execution model closest to the reference's architecture: one
compiled program per stage, each pinned to its own device, with activations
relayed stage→stage (reference: per-node ``model.predict`` + socket relay,
src/node.py:103-108).  Here the relay is ``jax.device_put`` between devices
(host-mediated or direct device-to-device; no sockets, no serialization) and
pipelining across in-flight microbatches falls out of JAX's async dispatch —
the host issues work for many microbatches ahead of completion, which is the
analogue of the reference's bounded in-flight queue (src/node.py:114).

Use it to cross-check the SPMD engine (identical outputs required) and for
wildly heterogeneous stage shapes where the homogeneous SPMD buffer would be
wasteful (SURVEY.md §7 model B).
"""

from __future__ import annotations

import collections
import time
from typing import Any, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..obs import tracer
from ..partition.stage import StageSpec
from ..utils.metrics import PipelineMetrics


class MpmdPipeline:
    """Per-stage jit programs + device_put relay, with the same streaming
    contract as :class:`SpmdPipeline`: ``reset`` / ``push`` / ``flush`` /
    ``warmup`` / ``run`` — so ``mode="mpmd"`` is a drop-in fallback for the
    dispatcher, not just a batch oracle."""

    def __init__(self, stages: Sequence[StageSpec], params: dict[str, Any],
                 *, devices=None, microbatch: int = 1, compute_dtype=None):
        self.stages = list(stages)
        self.num_stages = n = len(self.stages)
        self.microbatch = microbatch
        devices = list(devices if devices is not None else jax.devices())
        # round-robin placement if fewer devices than stages (single-chip
        # debugging still works: every stage on the one device)
        self.devices = [devices[i % len(devices)] for i in range(n)]
        self.compute_dtype = jnp.dtype(compute_dtype) if compute_dtype else None

        # donate the activation so XLA reuses its buffer stage over stage
        # (the relay's HBM footprint stays one activation per in-flight
        # microbatch, like the SPMD transfer buffer); CPU has no donation,
        # skip it there to keep test logs clean
        donate = (1,) if jax.default_backend() != "cpu" else ()
        self._fns = [jax.jit(s.fn, donate_argnums=donate)
                     for s in self.stages]
        self._params = [
            jax.device_put(s.select_params(params), d)
            for s, d in zip(self.stages, self.devices)
        ]
        self.in_spec = self.stages[0].in_spec
        self.out_spec = self.stages[-1].out_spec
        self.metrics = PipelineMetrics(num_stages=n, microbatch=microbatch)
        self.metrics.bind()
        self.reset()

    # ------------------------------------------------------------------
    # streaming interface (mirrors SpmdPipeline)
    # ------------------------------------------------------------------

    def reset(self):
        """Empty the in-flight window."""
        self._inflight: collections.deque[tuple[jax.Array, bool]] = \
            collections.deque()

    def _issue(self, x_np) -> jax.Array:
        """Issue one microbatch through every stage without blocking —
        JAX async dispatch is the in-flight pipelining (the reference's
        bounded queue, src/node.py:114)."""
        x = jnp.asarray(x_np, self.in_spec.dtype)
        if self.compute_dtype is not None and jnp.issubdtype(
                self.in_spec.dtype, jnp.floating):
            x = x.astype(self.compute_dtype)
        x = jax.device_put(x, self.devices[0])
        for k in range(self.num_stages):
            y = self._fns[k](self._params[k], x)
            if k + 1 < self.num_stages \
                    and self.devices[k + 1] != self.devices[k]:
                y = jax.device_put(y, self.devices[k + 1])
            x = y
        return x

    def push(self, xs: np.ndarray, n_real: int | None = None):
        """Issue ``xs`` ([C, microbatch, *in_shape]); return microbatches
        that have left the in-flight window (depth = pipeline depth), in
        feed order — the same contract as ``SpmdPipeline.push``."""
        xs = np.asarray(xs)
        c = xs.shape[0]
        if n_real is None:
            n_real = c
        t0 = time.perf_counter()
        emitted = []
        for j in range(c):
            self._inflight.append((self._issue(xs[j]), j < n_real))
            while len(self._inflight) > self.num_stages:
                arr, real = self._inflight.popleft()
                if real:
                    emitted.append(arr)
                    self.metrics.inferences += self.microbatch
        # block on what we hand back (the oldest in-flight work — normally
        # already complete) so wall_s measures execution, not just async
        # enqueue; newer microbatches stay in flight
        if emitted:
            jax.block_until_ready(emitted)
        self.metrics.steps += c
        self.metrics.chunk_calls += 1
        dt = time.perf_counter() - t0
        self.metrics.wall_s += dt
        self.metrics.push_latency.record(dt)
        tr = tracer()
        if tr.enabled:
            tr.record("mpmd.push", t0, dt,
                      {"chunk": c, "n_real": n_real})
        return emitted

    def flush(self):
        """Drain the in-flight window; returns remaining outputs in order."""
        emitted = []
        t0 = time.perf_counter()
        while self._inflight:
            arr, real = self._inflight.popleft()
            if real:
                emitted.append(arr)
                self.metrics.inferences += self.microbatch
        if emitted:
            jax.block_until_ready(emitted)
        self.metrics.wall_s += time.perf_counter() - t0
        return emitted

    def warmup(self):
        """Compile every stage program on one bubble microbatch."""
        self.reset()
        bubble = np.zeros((1, self.microbatch) + self.in_spec.shape,
                          np.float32)
        self.push(bubble, n_real=0)
        self.flush()
        self.reset()

    # ------------------------------------------------------------------
    # batch convenience
    # ------------------------------------------------------------------

    def run(self, inputs: np.ndarray) -> np.ndarray:
        """[M, microbatch, *in_shape] -> [M, microbatch, *out_shape]."""
        inputs = np.asarray(inputs)
        self.reset()
        outs = self.push(inputs)
        outs.extend(self.flush())
        assert len(outs) == inputs.shape[0], (len(outs), inputs.shape[0])
        # ONE batched device->host drain: per-output device_get serialized
        # M transfers; handing the whole list over lets them overlap
        return np.stack([np.asarray(o, np.float32)
                         for o in jax.device_get(outs)])

    def __call__(self, inputs: np.ndarray) -> np.ndarray:
        return self.run(inputs)
