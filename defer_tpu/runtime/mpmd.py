"""MPMD relay pipeline — the correctness oracle / debug execution mode.

This is the execution model closest to the reference's architecture: one
compiled program per stage, each pinned to its own device, with activations
relayed stage→stage (reference: per-node ``model.predict`` + socket relay,
src/node.py:103-108).  Here the relay is ``jax.device_put`` between devices
(host-mediated or direct device-to-device; no sockets, no serialization) and
pipelining across in-flight microbatches falls out of JAX's async dispatch —
the host issues work for many microbatches ahead of completion, which is the
analogue of the reference's bounded in-flight queue (src/node.py:114).

Use it to cross-check the SPMD engine (identical outputs required) and for
wildly heterogeneous stage shapes where the homogeneous SPMD buffer would be
wasteful (SURVEY.md §7 model B).
"""

from __future__ import annotations

from typing import Any, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..partition.stage import StageSpec
from ..utils.metrics import PipelineMetrics
import time


class MpmdPipeline:
    def __init__(self, stages: Sequence[StageSpec], params: dict[str, Any],
                 *, devices=None, microbatch: int = 1, compute_dtype=None):
        self.stages = list(stages)
        self.num_stages = n = len(self.stages)
        self.microbatch = microbatch
        devices = list(devices if devices is not None else jax.devices())
        # round-robin placement if fewer devices than stages (single-chip
        # debugging still works: every stage on the one device)
        self.devices = [devices[i % len(devices)] for i in range(n)]
        self.compute_dtype = jnp.dtype(compute_dtype) if compute_dtype else None

        self._fns = [jax.jit(s.fn) for s in self.stages]
        self._params = [
            jax.device_put(s.select_params(params), d)
            for s, d in zip(self.stages, self.devices)
        ]
        self.in_spec = self.stages[0].in_spec
        self.out_spec = self.stages[-1].out_spec
        self.metrics = PipelineMetrics(num_stages=n)

    def run(self, inputs: np.ndarray) -> np.ndarray:
        """[M, microbatch, *in_shape] -> [M, microbatch, *out_shape].

        All M microbatches are issued without blocking; async dispatch keeps
        every stage device busy on a different in-flight microbatch.
        """
        inputs = np.asarray(inputs)
        m = inputs.shape[0]
        t0 = time.perf_counter()
        outs = []
        for i in range(m):
            x = jnp.asarray(inputs[i], self.in_spec.dtype)
            if self.compute_dtype is not None and jnp.issubdtype(
                    self.in_spec.dtype, jnp.floating):
                x = x.astype(self.compute_dtype)
            x = jax.device_put(x, self.devices[0])
            for k in range(self.num_stages):
                y = self._fns[k](self._params[k], x)
                if k + 1 < self.num_stages \
                        and self.devices[k + 1] != self.devices[k]:
                    y = jax.device_put(y, self.devices[k + 1])
                x = y
            outs.append(x)
        result = np.stack([np.asarray(jax.device_get(o), np.float32)
                           for o in outs])
        self.metrics.wall_s += time.perf_counter() - t0
        self.metrics.inferences += m * self.microbatch
        return result

    def __call__(self, inputs: np.ndarray) -> np.ndarray:
        return self.run(inputs)
