"""Standalone stage-node processes: the multi-process MPMD chain.

Reference parity: the reference's compute node is a separate process on
another machine that receives its partition, then serves the chain forever —
recv activation, predict, relay to its successor (reference
src/node.py:80-108, boot at src/node.py:110-127).  The last node relays back
to the dispatcher (reference src/dispatcher.py:51-55).

The TPU-native redesign keeps the topology but none of the machinery:

* The partition arrives as a *compiled artifact* — StableHLO + weights
  (``utils/export.py``) loaded with zero model code — not Keras JSON
  rebuilt layer by layer (src/node.py:31-37).
* One typed framed connection per hop (``transport/framed.py``) instead of
  three fixed ports; the hop codec (raw / lzb / blockfloat) is the ZFP+LZ4
  analogue and is *symmetric* (the reference's decode sides are buggy,
  SURVEY.md §3.5).
* Readiness is connect-with-retry, not 5-second poll loops
  (src/node.py:33,96), and shutdown is an in-band END frame that cascades
  down the chain, not process kill.

The SPMD mesh engine (``runtime/spmd.py``) is the primary execution model;
this chain exists for the reference's one topology it doesn't cover —
stages as separate processes/hosts with a network between them.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import tempfile
import time
from typing import Any, Sequence

import numpy as np

from ..transport.framed import (K_END, K_TENSOR, recv_frame, send_end,
                                send_frame)


def _connect_retry(host: str, port: int, timeout_s: float = 30.0
                   ) -> socket.socket:
    """Connect, retrying while the peer boots (replaces the reference's
    sleep-5 polling rendezvous, src/node.py:95-96)."""
    deadline = time.monotonic() + timeout_s
    delay = 0.05
    while True:
        try:
            return socket.create_connection((host, port), timeout=timeout_s)
        except OSError:
            if time.monotonic() >= deadline:
                raise
            time.sleep(delay)
            delay = min(delay * 2, 1.0)


def _parse_hostport(s: str, default_host: str = "127.0.0.1"
                    ) -> tuple[str, int]:
    host, _, port = s.rpartition(":")
    return (host or default_host), int(port)


class StageNode:
    """One compute node of a process chain: recv -> stage fn -> relay.

    ``python -m defer_tpu node --artifact stage_k.zip --listen :5000
    --next host:5000`` is the working equivalent of the reference's
    ``python node.py`` (src/node.py:126-127).
    """

    def __init__(self, artifact: str, listen: str, next_hop: str,
                 *, codec: str = "raw"):
        from ..utils.export import load_stage
        # bind before the (slow: jax import + StableHLO deserialize)
        # artifact load so upstream connect-retries land as soon as the
        # process exists
        host, port = _parse_hostport(listen, "0.0.0.0")
        self._srv = socket.create_server((host, port))
        self.address = self._srv.getsockname()
        self.fn, self.manifest = load_stage(artifact)
        self.next_hop = _parse_hostport(next_hop)
        self.codec = codec

    def serve(self, *, connect_timeout_s: float = 30.0) -> int:
        """Accept one upstream connection and relay until its END frame.

        Returns the number of tensors processed.  The END frame is
        forwarded downstream before closing, so shutdown cascades through
        the chain to the dispatcher's result server.
        """
        conn, _ = self._srv.accept()
        out = _connect_retry(*self.next_hop, timeout_s=connect_timeout_s)
        n = 0
        want = tuple(self.manifest["in_shape"])
        try:
            while True:
                kind, value = recv_frame(conn)
                if kind == K_END:
                    send_end(out)
                    return n
                if kind != K_TENSOR:
                    raise ValueError(f"unexpected frame kind {kind}")
                if tuple(value.shape[1:]) != want:
                    raise ValueError(
                        f"stage {self.manifest['index']} expects sample "
                        f"shape {want}, got {tuple(value.shape[1:])}")
                y = np.asarray(self.fn(value))
                send_frame(out, y, codec=self.codec)
                n += 1
        finally:
            out.close()
            conn.close()
            self._srv.close()


class ChainDispatcher:
    """Drives a chain of stage-node processes from one controller.

    Opens the result server (the reference dispatcher's own port 5000 role,
    src/dispatcher.py:95-105), streams inputs to node 0, and yields results
    in order.  Strictly in-flight-window'd so the chain stays full without
    unbounded buffering.
    """

    def __init__(self, first_hop: str, *, listen: str = "127.0.0.1:0",
                 codec: str = "raw", window: int = 64,
                 timeout_s: float = 180.0):
        host, port = _parse_hostport(listen)
        self._res_srv = socket.create_server((host, port))
        self._res_srv.settimeout(timeout_s)  # a dead chain fails, not hangs
        self.result_address = self._res_srv.getsockname()
        self.first_hop = first_hop
        self.codec = codec
        self.window = window
        self.timeout_s = timeout_s
        self._send_sock: socket.socket | None = None
        self._res_conn: socket.socket | None = None

    def _ensure_connected(self):
        if self._send_sock is None:
            # generous: every node in the chain cold-imports jax first
            self._send_sock = _connect_retry(
                *_parse_hostport(self.first_hop), timeout_s=self.timeout_s)
        if self._res_conn is None:
            self._res_conn, _ = self._res_srv.accept()
            self._res_conn.settimeout(self.timeout_s)

    def stream(self, inputs) -> list[np.ndarray]:
        """Send every input through the chain; return outputs in order."""
        outs: list[np.ndarray] = []
        self._ensure_connected()
        in_flight = 0
        for x in inputs:
            send_frame(self._send_sock, np.asarray(x), codec=self.codec)
            in_flight += 1
            if in_flight >= self.window:
                outs.append(self._recv_tensor())
                in_flight -= 1
        while in_flight:
            outs.append(self._recv_tensor())
            in_flight -= 1
        return outs

    def _recv_tensor(self) -> np.ndarray:
        """One in-order result frame; loud protocol check (not an assert:
        ``python -O`` strips asserts, and an early END from a node that died
        mid-stream must raise, not silently mis-drain)."""
        kind, y = recv_frame(self._res_conn)
        if kind != K_TENSOR:
            raise ConnectionError(
                f"chain returned frame kind {kind!r} while results were "
                f"still in flight (a stage node died and cascaded END?)")
        return y

    def close(self):
        """Drain the chain (best effort) and close every socket.

        The graceful END handshake is wrapped so a chain that already died
        mid-stream can't mask the original failure with a secondary
        BrokenPipe/EOF from the teardown itself."""
        try:
            if self._send_sock is not None:
                send_end(self._send_sock)
                if self._res_conn is not None:
                    # drain any leftover in-flight frames until the END
                    # cascades through
                    while True:
                        kind, _ = recv_frame(self._res_conn)
                        if kind == K_END:
                            break
        except (OSError, ConnectionError, ValueError):
            pass  # teardown after failure: keep the root cause
        finally:
            if self._send_sock is not None:
                self._send_sock.close()
            if self._res_conn is not None:
                self._res_conn.close()
            self._res_srv.close()


def _free_ports(n: int) -> list[int]:
    socks = [socket.create_server(("127.0.0.1", 0)) for _ in range(n)]
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def run_chain(stages: Sequence, params: dict[str, Any], inputs,
              *, batch: int = 1, codec: str = "raw",
              artifact_dir: str | None = None,
              env: dict[str, str] | None = None) -> list[np.ndarray]:
    """Export, spawn one OS process per stage, stream, and tear down.

    The one-call analogue of the reference's whole deployment procedure
    (start N ``node.py`` processes, run the dispatcher, src/dispatcher.py:
    44-65 + test/test.py) — used by the CLI ``chain`` command and the
    multi-process integration test.

    ``env`` overrides the child environment.  By default children are
    pinned to the CPU backend: a local chain is a topology demonstration,
    and N child processes racing the parent for a single-client TPU would
    deadlock (this host's tunnel admits exactly one client).  Real
    multi-host deployments run ``python -m defer_tpu node`` per host with
    each host's own accelerator environment instead.
    """
    from ..utils.export import export_pipeline

    tmp = None
    if artifact_dir is None:
        tmp = tempfile.TemporaryDirectory(prefix="defer_chain_")
        artifact_dir = tmp.name
    try:
        paths = export_pipeline(stages, params, artifact_dir, batch=batch)
        n = len(paths)
        ports = _free_ports(n + 1)  # node listen ports + result port
        result_port = ports[-1]

        child_env = dict(os.environ)
        if env is None:
            env = {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
                   "XLA_FLAGS": "--xla_force_host_platform_device_count=1"}
        child_env.update(env)

        procs, logs = [], []
        for i, p in enumerate(paths):
            nxt = (f"127.0.0.1:{ports[i + 1]}" if i + 1 < n
                   else f"127.0.0.1:{result_port}")
            # log to files, not PIPEs: an undrained pipe fills and
            # deadlocks a chatty child mid-chain
            lf = open(os.path.join(artifact_dir, f"node_{i}.log"), "w+")
            logs.append(lf)
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "defer_tpu", "node",
                 "--artifact", p, "--listen", f"127.0.0.1:{ports[i]}",
                 "--next", nxt, "--codec", codec],
                env=child_env, stdout=lf, stderr=subprocess.STDOUT))

        disp = ChainDispatcher(f"127.0.0.1:{ports[0]}",
                               listen=f"127.0.0.1:{result_port}",
                               codec=codec)
        try:
            outs = disp.stream(inputs)
        finally:
            disp.close()
            for pr in procs:
                try:
                    pr.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    pr.kill()
        for i, pr in enumerate(procs):
            if pr.returncode not in (0, None):
                logs[i].seek(0)
                raise RuntimeError(
                    f"stage node {i} exited rc={pr.returncode}: "
                    f"{logs[i].read()[-2000:]}")
        return outs
    finally:
        for lf in locals().get("logs", []):
            lf.close()
        if tmp is not None:
            tmp.cleanup()
